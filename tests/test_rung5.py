"""Rung 5 of the config ladder: 100k groups × 5 peer slots with
membership-change and leader-transfer churn ROLLING THROUGH the load
(BASELINE.md ladder, final rung; reference scaling claim README.md
Performance § / `docs/nodes.png`).

Rung 4 (tests/test_rung4.py) runs churn phases after the load phase; the
rung-5 ladder row asks for churn *during* sustained load — thousands of
idle-group recycles, membership changes, and leader transfers per round
while every surviving group keeps committing, with commitIndex asserted
bit-identical to full scalar Raft oracles on a sampled subset every
round (the "bit-identical to pure-scalar path under Jepsen/Knossos"
clause — the linearizability harness proper runs in test_chaos_tcp.py;
here the differential oracle plays that role at scale).

Marked slow: one run is a few minutes on the 8-vCPU CI box.
"""
from __future__ import annotations

import time

import numpy as np
import pytest

from dragonboat_tpu.tpuquorum import TpuQuorumCoordinator
from dragonboat_tpu.wire import Entry, Message, MessageType as MT

from tests.test_rung4 import FakeNode, _assert_parity, _mk_oracle

# heavy multi-NodeHost tests serialize on one xdist worker
# (--dist loadgroup): 4-way-parallel multiprocess clusters
# starve each other on an 8-vCPU box
pytestmark = [pytest.mark.slow, pytest.mark.xdist_group("heavy-multiprocess")]

N = 100_000
SAMPLE = 128
PEERS = [1, 2, 3, 4, 5]
CHURN_BLOCK = 2_048  # rows recycled per round, mid-load


@pytest.mark.slow
def test_rung5_100k_groups_churn_under_load():
    coord = TpuQuorumCoordinator(capacity=N, n_peers=5, drive_ticks=False)
    try:
        eng = coord.eng
        # --- sampled groups: real scalar oracles through the coordinator
        oracles = {}
        for g in range(SAMPLE):
            cid = 1 + g
            r = _mk_oracle(cid)
            n = FakeNode(cid, r)
            r.offload = coord
            oracles[cid] = n
            coord._nodes[cid] = n
            with coord._mu:
                coord._sync_row_locked(n)
        # --- bulk groups: engine rows driven by the block-ingest path
        with coord._mu:
            for g in range(SAMPLE, N):
                cid = 1 + g
                eng.add_group(cid, node_ids=PEERS, self_id=1)
                eng.set_leader(cid, term=1, term_start=1, last_index=1)
            eng._upload_dirty()

        # per-group relative commit progress (churned groups restart at 1)
        base = {1 + g: 1 for g in range(SAMPLE, N)}
        next_fresh_cid = 1_000_000
        reads = writes = recycled = 0
        rounds = 6
        t0 = time.perf_counter()
        for rnd in range(1, rounds + 1):
            # --- rolling membership churn DURING load: recycle a block of
            # bulk rows (remove_group frees the row; add_group reuses it)
            victims = sorted(base)[(rnd - 1) * CHURN_BLOCK:rnd * CHURN_BLOCK]
            with coord._mu:
                for cid in victims:
                    eng.remove_group(cid)
                    del base[cid]
                for _ in victims:
                    cid = next_fresh_cid
                    next_fresh_cid += 1
                    eng.add_group(cid, node_ids=PEERS, self_id=1)
                    eng.set_leader(cid, term=1, term_start=1, last_index=1)
                    base[cid] = 1
                eng._upload_dirty()
            recycled += len(victims)

            # --- bulk writes: every live bulk group appends one entry,
            # acked by self + 2 followers (quorum of 5)
            cids = np.fromiter(base.keys(), np.int64, len(base))
            rows = np.array([eng.groups[c].row for c in base], np.int32)
            rels = np.array(
                [base[c] + 1 for c in base], np.int32
            )
            nb = rows.size
            with coord._mu:
                eng.ack_block(
                    np.concatenate([rows, rows, rows]),
                    np.concatenate([
                        np.zeros(nb, np.int32), np.ones(nb, np.int32),
                        np.full(nb, 2, np.int32),
                    ]),
                    np.concatenate([rels, rels, rels]),
                )
            for c in base:
                base[c] += 1

            # --- sampled oracles in lockstep through the staging API
            for cid, node in oracles.items():
                r = node.peer.raft
                if not r.is_leader():
                    continue
                r.handle(Message(
                    from_=1, to=1, type=MT.PROPOSE, entries=[Entry(cmd=b"x")]
                ))
                idx = r.log.last_index()
                for p in (2, 3):
                    r.handle(Message(
                        from_=p, to=1, term=r.term, type=MT.REPLICATE_RESP,
                        log_index=idx,
                    ))
                    coord.ack(cid, p, idx)
            coord.flush()
            writes += nb + SAMPLE

            # --- mixed 9:1 read-side probe: commit-watermark queries.
            # Under coord._mu: the background round thread's step()
            # donates the previous device state (donate_argnums), so an
            # unlocked read could touch a deleted buffer mid-dispatch.
            step = max(1, len(cids) // (9 * 64))
            with coord._mu:
                for c in cids[::step]:
                    eng.committed_index(int(c))
                    reads += 1

            # --- membership change on a rotating oracle slice, mid-load:
            # 5 -> 4 voters (round odd) or back 4 -> 5 (round even)
            lo = ((rnd - 1) * 16) % SAMPLE
            for cid in list(oracles)[lo:lo + 16]:
                node = oracles[cid]
                r = node.peer.raft
                with node.raft_mu:
                    if 5 in r.remotes:
                        r.remove_node(5)
                    else:
                        r.add_node(5)
                coord.membership_changed(cid)

            # --- leader transfer on a different rotating slice, mid-load:
            # step down, win a fresh election at a higher term
            lo = (16 + (rnd - 1) * 16) % SAMPLE
            for cid in list(oracles)[lo:lo + 8]:
                node = oracles[cid]
                r = node.peer.raft
                with node.raft_mu:
                    r.become_follower(r.term + 1, 2)
                coord.set_follower(cid, r.term)
                with node.raft_mu:
                    r.handle(Message(from_=1, to=1, type=MT.ELECTION))
                assert r.is_candidate(), cid
                coord.set_candidate(cid, r.term)
                coord.vote(cid, 1, True)
                for p in (2, 3):
                    r.handle(Message(
                        from_=p, to=1, term=r.term,
                        type=MT.REQUEST_VOTE_RESP,
                    ))
                    coord.vote(cid, p, True)
            coord.flush()
            # the election outcome lands via offload_election outside the
            # coordinator lock; re-seat each new leader's row watermarks
            deadline = time.time() + 8
            for cid in list(oracles)[lo:lo + 8]:
                r = oracles[cid].peer.raft
                while not r.is_leader() and time.time() < deadline:
                    time.sleep(0.01)
                assert r.is_leader(), cid
                coord.set_leader(
                    cid, term=r.term, term_start=r.log.last_index(),
                    last_index=r.log.last_index(),
                )

            # --- bit-identity on every sampled group, every round
            _assert_parity(
                eng, oracles, list(oracles), f"round {rnd}", mu=coord._mu
            )

        elapsed = time.perf_counter() - t0
        # spot-check bulk commit progress: survivors advanced every round
        # they were alive; freshly recycled groups advanced since rebirth
        with coord._mu:
            for c in (sorted(base)[len(base) // 2], max(base)):
                assert eng.committed_index(c) == base[c], c
        assert recycled == rounds * CHURN_BLOCK
        print(
            f"\nrung5: {N} groups x {rounds} rounds, "
            f"{recycled} recycled, {writes / elapsed:.0f} writes/s "
            f"{reads / elapsed:.0f} reads/s (coordinator path, CPU backend)"
        )
    finally:
        coord.stop()
