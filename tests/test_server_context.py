"""ServerContext tests: dir locking, layout, compatibility flag file.

Reference: ``internal/server/context.go:73-378`` +
``internal/settings/hard.go:124-137`` (VERDICT r2 item 6 done-criteria:
second NodeHost on the same dir fails fast; a changed hard setting
refuses to open).
"""
from __future__ import annotations

import os

import pytest

from dragonboat_tpu import Config, NodeHostConfig
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.server.context import (
    HardSettingsChangedError,
    LockDirectoryError,
    NotOwnerError,
    ServerContext,
)
from dragonboat_tpu.settings import Hard
from dragonboat_tpu.transport import ChanRouter, ChanTransport


def _nhconfig(tmp_path, addr="ctx1:1", router=None):
    router = router or ChanRouter()
    return NodeHostConfig(
        node_host_dir=str(tmp_path),
        rtt_millisecond=100,
        raft_address=addr,
        raft_rpc_factory=lambda s, rh, ch: ChanTransport(s, rh, ch, router=router),
        expert=ExpertConfig(quorum_engine="scalar"),
    )


def test_layout_uses_hostname_and_deployment_id(tmp_path):
    cfg = _nhconfig(tmp_path)
    ctx = ServerContext(cfg)
    did = cfg.get_deployment_id()
    data_dir, lldir = ctx.get_logdb_dirs(did)
    # hostname lives in the flag file, not the path (a renamed host must
    # hit HostnameChangedError, not a fresh empty directory)
    assert ctx.hostname not in data_dir
    assert f"{did:020d}" in data_dir
    sd = ctx.get_snapshot_dir(did, 7, 2)
    assert "snapshot-part-" in sd and sd.endswith("snapshot-7-2")


def test_second_nodehost_on_same_dir_fails_fast(tmp_path):
    router = ChanRouter()
    nh = NodeHost(_nhconfig(tmp_path, router=router))
    try:
        # same dir, same address: the flock is held by the live NodeHost
        with pytest.raises(LockDirectoryError):
            NodeHost(_nhconfig(tmp_path, router=ChanRouter()))
    finally:
        nh.stop()
    # after a clean stop the lock is released and reopening works
    nh2 = NodeHost(_nhconfig(tmp_path, router=ChanRouter()))
    nh2.stop()


def test_dir_owned_by_other_address_rejected(tmp_path):
    nh = NodeHost(_nhconfig(tmp_path, addr="owner:1"))
    nh.stop()
    with pytest.raises(NotOwnerError):
        NodeHost(_nhconfig(tmp_path, addr="intruder:1"))


def test_changed_hard_setting_refuses_to_open(tmp_path):
    nh = NodeHost(_nhconfig(tmp_path))
    nh.stop()
    old = Hard.logdb_entry_batch_size
    Hard.logdb_entry_batch_size = old + 1
    try:
        with pytest.raises(HardSettingsChangedError):
            NodeHost(_nhconfig(tmp_path))
    finally:
        Hard.logdb_entry_batch_size = old
    # restored settings open fine again
    nh2 = NodeHost(_nhconfig(tmp_path))
    nh2.stop()


def test_corrupted_flag_file_rejected(tmp_path):
    from dragonboat_tpu.server.context import FLAG_FILENAME, IncompatibleDataError

    cfg = _nhconfig(tmp_path)
    nh = NodeHost(cfg)
    nh.stop()
    ctx = ServerContext(cfg)
    data_dir, _ = ctx.get_logdb_dirs(cfg.get_deployment_id())
    fp = os.path.join(data_dir, FLAG_FILENAME)
    with open(fp, "r+b") as f:
        f.seek(10)
        f.write(b"\xff")
    with pytest.raises(IncompatibleDataError):
        NodeHost(cfg)


def test_restart_same_owner_ok(tmp_path):
    """Same address reopening its own dir is the normal restart path."""
    router = ChanRouter()
    for _ in range(2):
        nh = NodeHost(_nhconfig(tmp_path, router=ChanRouter()))
        nh.stop()
