"""Group-axis mesh sharding of the live quorum engine.

The reference scales by partitioning groups over worker goroutines
(``execengine.go:654-706``, ``clusterID % workers``); the TPU-native
analog partitions the state tensors' GROUP AXIS over a
``jax.sharding.Mesh`` (``ops/sharding.py``) — each device steps its
slice of groups with zero steady-state collectives.  conftest.py forces
an 8-device virtual CPU platform, so these tests exercise the same GSPMD
partitioner a real multi-chip mesh uses.

Three layers:
1. bare engine on an 8-device mesh: scalar-oracle commit differential
   (the ``dryrun_multichip`` scenario, in-suite)
2. the live ``TpuQuorumCoordinator`` built with ``mesh_devices=8``
   (``ExpertConfig.engine_mesh_devices``): state verifiably sharded
3. full stack: 3 NodeHosts whose engines are 8-way sharded, real
   elections + propose/read + commit parity
"""
import random
import time

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dragonboat_tpu import Config, NodeHostConfig, Result
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.ops.engine import BatchedQuorumEngine
from dragonboat_tpu.ops.sharding import GROUP_AXIS, make_mesh
from dragonboat_tpu.raft import InMemLogDB, Raft
from dragonboat_tpu.transport import ChanRouter, ChanTransport
from dragonboat_tpu.wire import Entry, Message, MessageType as MT

N_DEV = 8


def _mesh_sharding():
    devices = jax.local_devices(backend="cpu")
    assert len(devices) >= N_DEV, "conftest must force 8 CPU devices"
    mesh = make_mesh(np.array(devices[:N_DEV]))
    return NamedSharding(mesh, P(GROUP_AXIS))


def _is_group_sharded(arr) -> bool:
    spec = getattr(arr.sharding, "spec", None)
    return spec is not None and len(spec) >= 1 and spec[0] == GROUP_AXIS


def test_engine_sharded_commit_differential():
    """64 groups sharded over 8 devices: seeded elections fired by device
    ticks, then commit rounds with the FULL commit vector asserted
    bit-identical to per-group scalar oracles."""
    n_groups = 64
    rng = random.Random(11)
    eng = BatchedQuorumEngine(
        n_groups, n_peers=5, event_cap=4 * n_groups,
        sharding=_mesh_sharding(),
    )
    assert _is_group_sharded(eng.dev.match)
    oracles = {}
    for g in range(n_groups):
        cid = g + 1
        peers = [1, 2, 3] if cid % 2 else [1, 2, 3, 4, 5]
        r = Raft(
            Config(cluster_id=cid, node_id=1, election_rtt=10,
                   heartbeat_rtt=1),
            InMemLogDB(), seed=cid,
        )
        for p in peers:
            r.add_node(p)
        oracles[cid] = (r, peers)
        eng.add_group(
            cid, node_ids=peers, self_id=1, election_timeout=10,
            rand_timeout=r.randomized_election_timeout,
        )
        r.become_candidate()
        eng.set_candidate(cid, term=r.term)
        for p in peers:
            if p != 1:
                r.handle(Message(from_=p, to=1, term=r.term,
                                 type=MT.REQUEST_VOTE_RESP, reject=False))
            eng.vote(cid, p, True)
        assert r.is_leader()
        eng.set_leader(cid, term=r.term, term_start=r.log.last_index(),
                       last_index=r.log.last_index())
    for rnd in range(40):
        for cid, (r, peers) in oracles.items():
            if rng.random() < 0.7:
                r.handle(Message(from_=1, to=1, type=MT.PROPOSE,
                                 entries=[Entry(cmd=b"x")]))
                idx = r.log.last_index()
                eng.ack(cid, 1, idx)
                followers = [p for p in peers if p != 1]
                rng.shuffle(followers)
                for p in followers[: rng.randrange(0, len(followers) + 1)]:
                    r.handle(Message(from_=p, to=1, term=r.term,
                                     type=MT.REPLICATE_RESP, log_index=idx))
                    eng.ack(cid, p, idx)
        eng.step(do_tick=False)
        for cid, (r, _) in oracles.items():
            assert eng.committed_index(cid) == r.log.committed, (rnd, cid)
        # the sharded state stays sharded across dispatches
        assert _is_group_sharded(eng.dev.match)


def test_coordinator_shards_when_configured():
    from dragonboat_tpu.tpuquorum import TpuQuorumCoordinator

    coord = TpuQuorumCoordinator(capacity=60, mesh_devices=N_DEV)
    try:
        # capacity rounds up to a device multiple and state is sharded
        assert coord.eng.n_groups % N_DEV == 0
        assert _is_group_sharded(coord.eng.dev.match)
        assert _is_group_sharded(coord.eng.dev.committed)
    finally:
        coord.stop()


def test_full_stack_sharded_engine():
    """3 NodeHosts, each with an 8-way group-sharded engine: device-tick
    elections + committed proposals through the full stack (shared
    harness with ``__graft_entry__.dryrun_multichip`` phase D).  Load is
    sized for the 2-vCPU CI box: mesh coordinators shard over per-shard
    single-device engines (ops/mesh.py — the old process-wide
    ``_MULTIDEV_MU`` serialization is gone), but three 8-shard
    coordinators on two vCPUs still timeslice, so wall time scales with
    groups × writes."""
    from dragonboat_tpu.testing import run_sharded_stack_check

    assert run_sharded_stack_check(N_DEV, groups=16, writes_per_group=3) == 48
