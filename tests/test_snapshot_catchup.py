"""Streamed-snapshot catch-up for a far-behind restarted follower.

Round-4 soak caught a wedge here: a restore Update can carry BOTH the
snapshot and log entries past it, and appending the entries before the
LogReader window moved tripped the gap check — the committer then
retried the same update forever and the replica froze (applied below
commit through a 90s settle).  This pins the deterministic shape: a
follower restarts so far behind a compacted leader log that catch-up
MUST stream a snapshot while writes keep racing it.
"""
from __future__ import annotations

import socket

from tests import loadwait
import threading
import time

import pytest

from dragonboat_tpu import Config, NodeHost, NodeHostConfig, Result
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.native import natraft

# heavy multi-NodeHost tests serialize on one xdist worker
# (--dist loadgroup): 4-way-parallel multiprocess clusters
# starve each other on an 8-vCPU box
pytestmark = [pytest.mark.skipif(
    not natraft.available(), reason="libnatraft unavailable"
), pytest.mark.xdist_group("heavy-multiprocess")]

RTT = 20
CID = 55


class KVSM:
    def __init__(self, cluster_id, node_id):
        self.kv = {}

    def update(self, cmd):
        k, v = cmd.decode().split("=", 1)
        self.kv[k] = v
        return Result(value=len(self.kv))

    def lookup(self, query):
        return self.kv.get(query)

    def save_snapshot(self, w, files, done):
        import json

        data = json.dumps(sorted(self.kv.items())).encode()
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, files, done):
        import json

        n = int.from_bytes(r.read(8), "little")
        self.kv = dict(json.loads(r.read(n).decode()))

    def close(self):
        pass


def _ports(n):
    return loadwait.ports(n)


def _mk(i, addrs, tmp_path, sms):
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=str(tmp_path / f"nh{i}"),
            rtt_millisecond=RTT,
            raft_address=addrs[i],
            expert=ExpertConfig(fast_lane=True, logdb_shards=2),
        )
    )

    def create(cluster_id, node_id):
        sm = KVSM(cluster_id, node_id)
        sms[i] = sm
        return sm

    nh.start_cluster(
        addrs, False, create,
        Config(cluster_id=CID, node_id=i, election_rtt=10, heartbeat_rtt=1,
               # aggressive: the leader log compacts far behind a stopped
               # follower fast, forcing the streamed-snapshot path
               snapshot_entries=25, compaction_overhead=5),
    )
    return nh


def _leader(nhs, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        for nh in nhs.values():
            try:
                lid, ok = nh.get_leader_id(CID)
                if ok and lid in nhs:
                    return lid, nhs[lid]
            except Exception:
                pass
        time.sleep(0.05)
    raise AssertionError("no leader")


def test_far_behind_follower_streams_snapshot_under_load(tmp_path):
    addrs = {i: f"127.0.0.1:{p}" for i, p in enumerate(_ports(3), start=1)}
    sms = {}
    nhs = {i: _mk(i, addrs, tmp_path, sms) for i in (1, 2, 3)}
    stop = threading.Event()
    done = [0]
    try:
        nhs[1].get_node(CID).request_campaign()
        lid, leader = _leader(nhs)
        s = leader.get_noop_session(CID)

        def put(j, deadline):
            # retry timed-out proposes until the deadline: on a starved CI
            # box a single 10s-budget write can time out without implying
            # anything about snapshot catch-up (the thing under test)
            while True:
                rs = leader.propose(s, f"w{j}=a{j}".encode(), timeout=10.0)
                if rs.wait(30.0).completed:
                    return
                assert time.time() < deadline, f"write w{j} never completed"

        deadline = time.time() + 240
        for j in range(40):
            put(j, deadline)
        # stop a follower, push FAR past its log (many snapshot cycles)
        fid = next(i for i in (1, 2, 3) if i != lid)
        nhs[fid].stop()
        del nhs[fid]
        for j in range(40, 400):
            put(j, deadline)

        # restart it with writes RACING the snapshot catch-up: the restore
        # update then carries entries chasing the installed snapshot
        def load():
            j = 400
            while not stop.is_set():
                j += 1
                try:
                    rs = leader.propose(
                        s, f"w{j}=a{j}".encode(), timeout=5.0
                    )
                    if rs.wait(5.0).completed:
                        done[0] = j
                except Exception:
                    time.sleep(0.02)

        t = threading.Thread(target=load, daemon=True)
        t.start()
        nhs[fid] = _mk(fid, addrs, tmp_path, sms)
        time.sleep(6.0)  # catch-up (snapshot stream + tail) under load
        stop.set()
        t.join(timeout=10)
        last = done[0] or 399
        deadline = time.time() + 60
        while time.time() < deadline:
            if all(
                sms[i].kv.get(f"w{last}") == f"a{last}" for i in (1, 2, 3)
            ):
                break
            time.sleep(0.2)
        for i in (1, 2, 3):
            assert sms[i].kv.get(f"w{last}") == f"a{last}", (
                i, len(sms[i].kv),
                nhs[i].get_node(CID).sm.get_last_applied(),
                nhs[i].get_node(CID).peer.raft.log.committed,
            )
    finally:
        stop.set()
        for nh in nhs.values():
            try:
                nh.stop()
            except Exception:
                pass
