"""Snapshot worker pool + snapshot-status feedback tests.

Reference: dedicated snapshot workers (``execengine.go:240-635``) so a slow
user snapshot never stalls other groups' applies, and the delayed
snapshot-status feedback (``feedback.go:23-129``) so a dropped status/ack
message cannot strand a follower in Snapshot state (VERDICT r2 item 7).
"""
from __future__ import annotations

import time

import pytest

from dragonboat_tpu import Config, NodeHost, NodeHostConfig, Result
from dragonboat_tpu.feedback import SnapshotFeedback
from dragonboat_tpu.transport import ChanRouter, ChanTransport

RTT_MS = 5


# ------------------------------------------------- feedback unit tests


def test_feedback_delays_push_until_release():
    pushed = []
    fb = SnapshotFeedback(lambda c, n, f: pushed.append((c, n, f)) or True,
                          push_delay_ms=1000)
    fb.add_status(1, 2, False, now_ms=0)
    fb.push_ready(now_ms=500)
    assert pushed == []  # still parked
    fb.push_ready(now_ms=1001)
    assert pushed == [(1, 2, False)]
    assert fb.pending_count() == 0


def test_feedback_confirm_accelerates_release():
    pushed = []
    fb = SnapshotFeedback(lambda c, n, f: pushed.append((c, n, f)) or True,
                          push_delay_ms=100000, confirmed_delay_ms=100)
    fb.add_status(1, 2, False, now_ms=0)
    fb.confirm(1, 2, now_ms=10)
    fb.push_ready(now_ms=50)
    assert pushed == []
    fb.push_ready(now_ms=111)
    assert pushed == [(1, 2, False)]


def test_feedback_retries_failed_push():
    """A status the node queue rejected is re-parked and re-pushed — the
    'dropped status message still recovers' guarantee."""
    attempts = []

    def push(c, n, f):
        attempts.append((c, n, f))
        return len(attempts) >= 3  # fail twice, then succeed

    fb = SnapshotFeedback(push, push_delay_ms=10, retry_delay_ms=10)
    fb.add_status(9, 3, True, now_ms=0)
    now = 11
    for _ in range(5):
        fb.push_ready(now_ms=now)
        now += 11
    assert attempts == [(9, 3, True)] * 3
    assert fb.pending_count() == 0


def test_feedback_failed_status_preserved_through_retry():
    seen = []
    fb = SnapshotFeedback(lambda c, n, f: seen.append(f) or False,
                          push_delay_ms=1, retry_delay_ms=1)
    fb.add_status(1, 2, True, now_ms=0)
    fb.push_ready(now_ms=5)
    fb.push_ready(now_ms=10)
    assert seen == [True, True]


# --------------------------------------- slow save doesn't stall applies


class SlowSnapSM:
    """save_snapshot blocks; updates are instant."""

    SAVE_SECONDS = 2.0

    def __init__(self, cluster_id, node_id):
        self.count = 0

    def update(self, cmd):
        self.count += 1
        return Result(value=self.count)

    def lookup(self, query):
        return self.count

    def save_snapshot(self, w, files, done):
        time.sleep(self.SAVE_SECONDS)
        w.write(self.count.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        self.count = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def test_slow_snapshot_save_does_not_block_other_groups():
    """Two groups on the same apply worker (cid % workers equal); a
    multi-second snapshot save on one must not delay the other's applies."""
    router = ChanRouter()

    def factory(src, rh, ch):
        return ChanTransport(src, rh, ch, router=router)

    nhs = [
        NodeHost(
            NodeHostConfig(
                node_host_dir=":memory:",
                rtt_millisecond=RTT_MS,
                raft_address=f"sp{i}:1",
                raft_rpc_factory=factory,
            )
        )
        for i in (1, 2, 3)
    ]
    addrs = {i: f"sp{i}:1" for i in (1, 2, 3)}
    # default engine: 4 step/apply workers → cids 1 and 5 share worker 1
    slow_cid, fast_cid = 1, 5
    try:
        for cid in (slow_cid, fast_cid):
            for i, nh in enumerate(nhs, 1):
                nh.start_cluster(
                    addrs, False, SlowSnapSM,
                    Config(cluster_id=cid, node_id=i, election_rtt=10,
                           heartbeat_rtt=1, snapshot_entries=0),
                )
            nhs[0].get_node(cid).request_campaign()
        deadline = time.time() + 20
        leaders = {}
        while len(leaders) < 2 and time.time() < deadline:
            for cid in (slow_cid, fast_cid):
                for nh in nhs:
                    lid, ok = nh.get_leader_id(cid)
                    if ok:
                        leaders[cid] = nhs[lid - 1]
            time.sleep(0.02)
        assert len(leaders) == 2
        # a few writes so there is something to snapshot
        for cid in (slow_cid, fast_cid):
            s = leaders[cid].get_noop_session(cid)
            rs = leaders[cid].propose(s, b"x", timeout=5.0)
            assert rs.wait(5.0).completed
        # kick the slow snapshot on every replica of slow_cid
        for nh in nhs:
            nh.get_node(slow_cid).request_snapshot(
                __import__(
                    "dragonboat_tpu.rsm", fromlist=["SSRequest"]
                ).SSRequest(type=1),
                timeout_s=30.0,
            )
        time.sleep(0.1)  # let the saves start on the snapshot pool
        # applies on the co-scheduled fast group must stay fast
        s = leaders[fast_cid].get_noop_session(fast_cid)
        t0 = time.perf_counter()
        for _ in range(5):
            rs = leaders[fast_cid].propose(s, b"y", timeout=5.0)
            assert rs.wait(5.0).completed
        elapsed = time.perf_counter() - t0
        assert elapsed < SlowSnapSM.SAVE_SECONDS / 2, (
            f"applies stalled behind the slow snapshot: {elapsed:.2f}s"
        )
    finally:
        for nh in nhs:
            nh.stop()
