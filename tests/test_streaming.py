"""On-disk SM snapshot streaming end-to-end.

Reference: ``internal/transport/job.go:43-248`` (per-transfer job + Sink),
``internal/rsm/chunkwriter.go``, ``node.go:718-738``.  VERDICT r2 item 5
done-criterion: a lagging on-disk-SM replica catches up via a streamed
snapshot over BOTH the chan and tcp transports.
"""
from __future__ import annotations

import socket
import time

import pytest

from dragonboat_tpu import Config, NodeHost, NodeHostConfig
from dragonboat_tpu.statemachine import IOnDiskStateMachine, Result, SMEntry
from dragonboat_tpu.transport import ChanRouter, ChanTransport

RTT = 10


class DiskKV(IOnDiskStateMachine):
    """In-memory stand-in with on-disk SEMANTICS (own 'durable' store,
    streaming snapshots); shared dict keyed per instance for inspection."""

    def __init__(self, cluster_id, node_id):
        self.kv = {}
        self.applied = 0

    def open(self, stopc) -> int:
        return self.applied

    def update(self, entries):
        for e in entries:
            k, v = bytes(e.cmd).decode().split("=", 1)
            self.kv[k] = v
            self.applied = e.index
            e.result = Result(value=len(self.kv))
        return entries

    def lookup(self, query):
        return self.kv.get(query)

    def sync(self) -> None:
        pass

    def prepare_snapshot(self):
        return dict(self.kv)

    def save_snapshot(self, ctx, w, done) -> None:
        import json

        data = json.dumps(sorted(ctx.items())).encode()
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, done) -> None:
        import json

        n = int.from_bytes(r.read(8), "little")
        self.kv = dict(json.loads(r.read(n).decode()))

    def close(self) -> None:
        pass


def _free_ports(n):
    out = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        out.append(s.getsockname()[1])
        s.close()
    return out


def _wait_leader(nhs, cid, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        for nh in nhs:
            lid, ok = nh.get_leader_id(cid)
            if ok:
                return nhs[lid - 1]
        time.sleep(0.02)
    raise AssertionError("no leader")


def _run_streaming_catchup(make_transport, addrs, tmp_path):
    """Replicas 1,2 run; replica 3 joins late with an empty store after the
    log was compacted — it can only catch up via a streamed snapshot."""
    CID = 1
    sms = {}

    def create(nh_idx):
        def f(cluster_id, node_id):
            sm = DiskKV(cluster_id, node_id)
            sms[nh_idx] = sm
            return sm

        return f

    nhs = []
    for i in (1, 2, 3):
        nhs.append(
            NodeHost(
                NodeHostConfig(
                    node_host_dir=str(tmp_path / f"nh{i}"),
                    rtt_millisecond=RTT,
                    raft_address=addrs[i],
                    raft_rpc_factory=make_transport,
                )
            )
        )
    cfg = lambda i: Config(
        cluster_id=CID, node_id=i, election_rtt=10, heartbeat_rtt=1,
        snapshot_entries=10, compaction_overhead=2,
    )
    try:
        # only replicas 1 and 2 start; 3 stays down
        for i in (1, 2):
            nhs[i - 1].start_on_disk_cluster(addrs, False, create(i), cfg(i))
        nhs[0].get_node(CID).request_campaign()
        leader = _wait_leader(nhs[:2], CID)
        s = leader.get_noop_session(CID)
        for j in range(60):  # >> snapshot_entries: snapshots + compaction run
            rs = leader.propose(s, f"k{j}=v{j}".encode(), timeout=10.0)
            assert rs.wait(10.0).completed
        time.sleep(1.0)  # let snapshot + compaction finish on the pool
        # now start replica 3: its log was never written and the leader's
        # log is compacted, so it must receive a streamed snapshot
        nhs[2].start_on_disk_cluster(addrs, False, create(3), cfg(3))
        deadline = time.time() + 60
        while time.time() < deadline:
            if sms.get(3) is not None and sms[3].kv.get("k59") == "v59":
                break
            time.sleep(0.1)
        assert sms.get(3) is not None and sms[3].kv.get("k59") == "v59", (
            f"lagging replica never caught up: "
            f"{len(sms.get(3).kv) if sms.get(3) else 'no sm'} keys"
        )
    finally:
        for nh in nhs:
            nh.stop()


def test_streaming_catchup_over_chan(tmp_path):
    router = ChanRouter()
    addrs = {i: f"st{i}:1" for i in (1, 2, 3)}

    def factory(src, rh, ch):
        return ChanTransport(src, rh, ch, router=router)

    _run_streaming_catchup(factory, addrs, tmp_path)


def test_streaming_catchup_over_tcp(tmp_path):
    ports = _free_ports(3)
    addrs = {i: f"127.0.0.1:{ports[i-1]}" for i in (1, 2, 3)}
    _run_streaming_catchup(None, addrs, tmp_path)
