"""Device-plane telemetry fold suite (ISSUE 20).

Contracts under test:

- **Differential**: the device aggregate (``kernels.telem_fold``
  harvested through ``BatchedQuorumEngine.telem_snapshot``) is
  bit-identical to a numpy host oracle computed from the same state —
  across sparse steps, the fused multi-round scan (including a
  mid-block ``stage_recycle``), and the mesh facade's host-side merge
  (including a live migration between shards);
- **telem OFF structural identity**: until ``enable_telem`` flips the
  latch, every dispatch runs ``has_telem=False``, the telem field
  never joins rare-path row syncs, the device array stays all-zero and
  ``telem_snapshot()`` is None;
- **aggregate sampler semantics** (synthetic samples): the
  ``commit_stall``/``apply_lag`` aggregate detectors open and close on
  fold contents with the same hysteresis discipline as the per-group
  path, a STALE fold (same seq) neither extends streaks nor closes
  events, raft_mu-budget ``busy`` rows mid-walk neither close open
  per-group detectors nor open spurious ones, and absence from the
  drill-down walk is not treated as group removal;
- **endpoints**: ``/metrics`` streams as chunked transfer on HTTP/1.1
  and byte-matches the monolithic writer, the cardinality guard warns
  once per family, ``/debug/telem`` 404s while the fold is off and
  serves the live snapshot in aggregate mode.
"""
from __future__ import annotations

import io
import json
import logging
import socket
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dragonboat_tpu import Config, NodeHostConfig, Result
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.events import MetricsRegistry
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.obs.health import HealthSampler
from dragonboat_tpu.ops.engine import BatchedQuorumEngine
from dragonboat_tpu.ops.kernels import (
    TELEM_LAG_BUCKETS,
    TELEM_STATES,
    TELEM_TOPK,
)
from dragonboat_tpu.ops.mesh import MeshQuorumEngine
from dragonboat_tpu.transport import ChanRouter, ChanTransport

from tests.loadwait import wait_until

RTT_MS = 5
CID = 940


# ----------------------------------------------------------------------
# host oracle
# ----------------------------------------------------------------------


def _shard_oracle(eng, prev_committed, k=None):
    """Recompute one shard's TelemAggregate from its device state with
    plain numpy — the independent twin of ``kernels.telem_fold``.
    ``prev_committed`` is the device ``telem_prev_committed`` captured
    BEFORE the dispatch (the fold compares against the previous fold's
    watermark, then advances it)."""
    dev = eng.dev
    live = np.asarray(dev.live)
    last = np.asarray(dev.last_index).astype(np.int64)
    comm = np.asarray(dev.committed).astype(np.int64)
    ns = np.asarray(dev.node_state).astype(np.int64)
    lag = np.where(live, np.maximum(last - comm, 0), 0)
    hist = [0] * TELEM_LAG_BUCKETS
    states = [0] * TELEM_STATES
    stalled = 0
    for r in np.nonzero(live)[0]:
        b = sum(
            1 for i in range(TELEM_LAG_BUCKETS - 1) if lag[r] >= (1 << i)
        )
        hist[b] += 1
        states[int(ns[r])] += 1
        if comm[r] == prev_committed[r] and lag[r] > 0:
            stalled += 1
    k = k if k is not None else eng.n_telem_topk
    masked = np.where(live, lag, -1)
    order = sorted(
        range(masked.shape[0]), key=lambda r: (-int(masked[r]), r)
    )[:k]
    topk = [
        (int(eng._row_cid[r]), int(masked[r]))
        for r in order
        if masked[r] >= 0 and eng._row_cid[r] >= 0
    ]
    return {
        "groups": int(live.sum()),
        "lag_hist": hist,
        "state_counts": states,
        "stalled": stalled,
        "read_slots": int(np.sum(np.asarray(dev.read_count) > 0)),
        "kv_ents": int(np.sum(np.asarray(dev.kv_ent_index) >= 0)),
        "topk": topk,
    }


_AGG_KEYS = (
    "groups", "lag_hist", "state_counts", "stalled",
    "read_slots", "kv_ents", "topk",
)


def _assert_matches(snap, oracle, tag=""):
    assert snap is not None, tag
    for key in _AGG_KEYS:
        got = snap[key]
        if key == "topk":
            got = [tuple(p) for p in got]
        assert got == oracle[key], (tag, key, got, oracle[key])


def _build(n_groups=12, n_peers=3, last_index=1, cap=256, telem=True):
    eng = BatchedQuorumEngine(n_groups, n_peers, event_cap=cap)
    if telem:
        eng.enable_telem()
    for cid in range(1, n_groups + 1):
        eng.add_group(cid, node_ids=list(range(1, n_peers + 1)), self_id=1)
        eng.set_leader(cid, term=1, term_start=1, last_index=last_index)
    eng._upload_dirty()
    return eng


def _prev(eng):
    return np.asarray(eng.dev.telem_prev_committed).copy()


# ----------------------------------------------------------------------
# engine-level differential
# ----------------------------------------------------------------------


def test_telem_sparse_steps_match_oracle():
    """Random ack schedules over several sparse dispatches: every
    harvested aggregate equals the numpy oracle bit-for-bit."""
    import random

    rng = random.Random(2001)
    g = 12
    eng = _build(g, last_index=20)
    for step in range(5):
        for _ in range(rng.randrange(1, 10)):
            cid = rng.randrange(1, g + 1)
            eng.ack(cid, 2, rng.choice([1, 2, 5, 9, 17, 20]))
        prev = _prev(eng)
        eng.step(do_tick=False)
        snap = eng.telem_snapshot()
        _assert_matches(snap, _shard_oracle(eng, prev), f"step{step}")
        assert snap["seq"] == step + 1
        # the fold advanced the device watermark to this fold's commit
        assert np.array_equal(
            _prev(eng), np.asarray(eng.dev.committed)
        )


def test_telem_stalled_semantics():
    """``stalled`` counts live groups whose commit watermark stayed
    FLAT since the previous fold while entries are pending — a group
    that commits between folds leaves the count."""
    eng = _build(4, last_index=10)
    for cid in (1, 2, 3):
        eng.ack(cid, 2, 5)  # commit 5, lag 5
    eng.step(do_tick=False)
    # first fold: 1-3 moved off the initial watermark; 4 never has
    assert eng.telem_snapshot()["stalled"] == 1
    # second fold: 1 and 2 stay flat with lag pending -> stalled; 3
    # advances; 4 is flat but has no lag (never committed, last==prev)
    eng.ack(3, 2, 9)
    prev = _prev(eng)
    eng.step(do_tick=False)
    snap = eng.telem_snapshot()
    _assert_matches(snap, _shard_oracle(eng, prev), "stalled")
    assert snap["stalled"] == 3  # groups 1, 2, and 4 (lag 10, never moved)
    assert (4, 10) in [tuple(p) for p in snap["topk"]]


def test_telem_topk_ties_break_toward_lower_row():
    """Equal lags must order by row — ``lax.top_k`` and the host oracle
    agree on (-lag, row)."""
    eng = _build(6, last_index=8)
    for cid in (2, 4, 5):
        eng.ack(cid, 2, 8)  # lag 0
    prev = _prev(eng)
    eng.step(do_tick=False)
    snap = eng.telem_snapshot()
    _assert_matches(snap, _shard_oracle(eng, prev), "ties")
    # rows 0, 2, 5 (cids 1, 3, 6) all sit at lag 8: row order decides
    assert [tuple(p) for p in snap["topk"]][:3] == [(1, 8), (3, 8), (6, 8)]


def test_telem_topk_k_override():
    eng = BatchedQuorumEngine(8, 3, event_cap=128)
    eng.enable_telem(topk=2)
    assert eng.n_telem_topk == 2
    for cid in range(1, 9):
        eng.add_group(cid, node_ids=[1, 2, 3], self_id=1)
        eng.set_leader(cid, term=1, term_start=1, last_index=4)
    eng._upload_dirty()
    eng.ack(1, 2, 1)
    prev = _prev(eng)
    eng.step(do_tick=False)
    snap = eng.telem_snapshot()
    assert len(snap["topk"]) == 2
    _assert_matches(snap, _shard_oracle(eng, prev), "k=2")


def test_telem_fused_multiround_matches_fresh_fold():
    """A K-round fused block folds ONCE on the final scanned state —
    the aggregate must equal the oracle over the post-block state with
    the pre-block watermark (monotone commits make that identical to a
    fresh single-round fold)."""
    import random

    rng = random.Random(2002)
    g = 10
    eng = _build(g, last_index=30)
    for _ in range(3):  # three fused blocks
        n_rounds = rng.randrange(2, 5)
        for _ in range(n_rounds):
            for _ in range(rng.randrange(1, 8)):
                cid = rng.randrange(1, g + 1)
                eng.ack(cid, 2, rng.choice([2, 7, 13, 28, 30]))
            eng.begin_round()
        prev = _prev(eng)
        res = eng.step_rounds(do_tick=False)
        assert res is not None
        snap = eng.telem_snapshot()
        _assert_matches(snap, _shard_oracle(eng, prev), "fused")
        assert snap["rounds"] == n_rounds


def test_telem_recycle_mid_block_resets_watermark():
    """A ``stage_recycle`` inside a fused block resets the recycled
    row's telem watermark in-program: the new tenant's stalled
    predicate compares against 0, never the old tenant's commit, and
    the top-K labels the NEW cluster id."""
    eng = _build(6, last_index=4)
    for cid in range(1, 7):
        eng.ack(cid, 2, 4)  # everyone commits 4, lag 0
    eng.begin_round()
    eng.step_rounds(do_tick=False)
    assert eng.telem_snapshot()["stalled"] == 0

    # recycle 3 -> 103 with a pending tail (last_index 9, commits 0)
    eng.stage_recycle(3, 103, term=2, term_start=0, last_index=9)
    eng.ack(1, 2, 2)  # keep the block non-empty for another group too
    eng.begin_round()
    prev = _prev(eng)
    prev[eng.groups[103].row] = 0  # in-program reset at round start
    eng.step_rounds(do_tick=False)
    snap = eng.telem_snapshot()
    _assert_matches(snap, _shard_oracle(eng, prev), "recycle")
    # the fresh tenant: flat at 0 with 9 pending -> stalled, worst lag
    assert snap["stalled"] == 1
    assert tuple(snap["topk"][0]) == (103, 9)
    assert 3 not in [p[0] for p in snap["topk"]]


def test_telem_mesh_merge_and_migration():
    """The mesh facade's merged snapshot equals the sum of per-shard
    oracles — histograms/counts add, top-K re-sorts by (-lag, cid) —
    and stays correct across a live ``migrate_group``."""
    devs = jax.local_devices(backend="cpu")
    assert len(devs) >= 2, "conftest must force multiple CPU devices"
    mesh = MeshQuorumEngine(16, 3, event_cap=128, devices=devs[:2])
    mesh.enable_telem(topk=4)
    assert mesh.telem_enabled
    assert mesh.telem_snapshot() is None
    for cid in range(1, 9):
        mesh.add_group(cid, node_ids=[1, 2, 3], self_id=1)
        mesh.set_leader(cid, term=1, term_start=1, last_index=2 * cid)
    for s in mesh.shards:
        s._upload_dirty()
    for cid in range(1, 9):
        mesh.ack(cid, 2, cid)  # commit cid, lag cid

    def dispatch_and_check(tag):
        prevs = [_prev(s) for s in mesh.shards]
        mesh.step(do_tick=True)  # do_tick dispatches EVERY shard
        oracles = [
            _shard_oracle(s, p, k=4)
            for s, p in zip(mesh.shards, prevs)
        ]
        snap = mesh.telem_snapshot()
        assert snap is not None and snap["shards"] == 2
        for key in ("groups", "stalled", "read_slots", "kv_ents"):
            assert snap[key] == sum(o[key] for o in oracles), (tag, key)
        for key in ("lag_hist", "state_counts"):
            merged = [
                sum(o[key][i] for o in oracles)
                for i in range(len(oracles[0][key]))
            ]
            assert snap[key] == merged, (tag, key)
        allk = sorted(
            (p for o in oracles for p in o["topk"]),
            key=lambda p: (-p[1], p[0]),
        )[:4]
        assert [tuple(p) for p in snap["topk"]] == allk, tag
        return snap

    s1 = dispatch_and_check("pre-migrate")
    assert s1["groups"] == 8

    # migrate the worst group to the other shard; the merge must keep
    # labelling it with the same cid and its (unchanged) lag
    worst = s1["topk"][0][0]
    target = 1 - mesh.shard_index(worst)
    assert mesh.migrate_group(worst, target)
    assert mesh.shard_index(worst) == target
    for s in mesh.shards:
        s._upload_dirty()
    s2 = dispatch_and_check("post-migrate")
    assert s2["groups"] == 8
    assert s2["topk"][0][0] == worst


# ----------------------------------------------------------------------
# telem OFF: structural identity
# ----------------------------------------------------------------------


def test_telem_off_structural_identity():
    """Until the latch flips, dispatches carry no fold: the snapshot
    stays None, the telem field never joins rare-path syncs, and the
    device watermark array is provably all-zero after real traffic."""
    eng = _build(8, last_index=6, telem=False)
    assert not eng._telem_used
    assert not eng.telem_enabled
    for cid in range(1, 9):
        eng.ack(cid, 2, 5)
    eng.step(do_tick=False)
    eng.ack(1, 2, 6)
    eng.begin_round()
    eng.step_rounds(do_tick=False)
    assert eng.telem_snapshot() is None
    for k in eng._TELEM_KEYS:
        assert k not in eng._sync_keys()
    assert not np.asarray(eng.dev.telem_prev_committed).any()
    # flipping the latch mid-life starts folding on the next dispatch
    eng.enable_telem()
    eng.ack(2, 2, 6)
    prev = _prev(eng)
    eng.step(do_tick=False)
    _assert_matches(
        eng.telem_snapshot(), _shard_oracle(eng, prev), "post-flip"
    )
    for k in eng._TELEM_KEYS:
        assert k in eng._sync_keys()


# ----------------------------------------------------------------------
# aggregate sampler semantics (synthetic samples)
# ----------------------------------------------------------------------


def _unit_sampler(**kw):
    return HealthSampler(nh=None, registry=MetricsRegistry(), **kw)


def _sample(groups=None, mono=None, telem=None, gone=()):
    s = {
        "ts": time.time(),
        "mono": mono if mono is not None else time.monotonic(),
        "groups": groups or {},
        "host": {"hostproc": None},
    }
    if telem is not None:
        s["aggregate"] = True
        s["telem"] = telem
        s["gone_cids"] = list(gone)
    return s


def _tel(seq, stalled=0, hist=None, topk=(), states=None):
    hist = list(hist) if hist is not None else [0] * TELEM_LAG_BUCKETS
    return {
        "seq": seq,
        "mono": time.monotonic(),
        "rounds": 1,
        "groups": sum(hist),
        "lag_hist": hist,
        "state_counts": list(states) if states else [0] * TELEM_STATES,
        "stalled": stalled,
        "read_slots": 0,
        "kv_ents": 0,
        "topk": [list(p) for p in topk],
    }


def _open_keys(hs):
    return sorted((e["detector"], e["key"]) for e in hs.open_events())


def test_unit_aggregate_commit_stall_streak_and_stale_seq():
    hs = _unit_sampler(aggregate=True, commit_stall_samples=2)
    hs.ingest(_sample(telem=_tel(1, stalled=3, topk=[(7, 40)])))
    assert not hs.open_events()  # streak 1 of 2
    hs.ingest(_sample(telem=_tel(2, stalled=3, topk=[(7, 40)])))
    assert _open_keys(hs) == [("commit_stall", "aggregate")]
    ev = hs.open_events()[0]
    assert ev["detail"]["topk"] == [[7, 40]]
    # a STALE fold (same seq: idle engine) must neither close the event
    # nor advance the streak bookkeeping
    for _ in range(3):
        hs.ingest(_sample(telem=_tel(2, stalled=0)))
    assert _open_keys(hs) == [("commit_stall", "aggregate")]
    assert hs._telem_stall_streak == 2
    # a FRESH clean fold closes it with a measured recovery
    hs.ingest(_sample(telem=_tel(3, stalled=0)))
    assert not hs.open_events()
    assert hs.recovery_stats()["commit_stall"]["n"] == 1


def test_unit_aggregate_apply_lag_tail_hysteresis():
    hs = _unit_sampler(aggregate=True, apply_lag_entries=100)
    # threshold 100 -> first all-over bucket is 8 (2^7 = 128 >= 100)
    assert HealthSampler._lag_tail_bucket(100) == 8
    hist = [0] * TELEM_LAG_BUCKETS
    hist[8] = 2
    hs.ingest(_sample(telem=_tel(1, hist=hist, topk=[(9, 200), (4, 130)])))
    assert _open_keys(hs) == [("apply_lag", "aggregate")]
    assert hs.open_events()[0]["detail"]["groups_over"] == 2
    # open -> the close threshold halves (50 -> bucket 7, 2^6 = 64):
    # groups draining into [64, 128) keep the event open...
    hist = [0] * TELEM_LAG_BUCKETS
    hist[7] = 1
    hs.ingest(_sample(telem=_tel(2, hist=hist)))
    assert _open_keys(hs) == [("apply_lag", "aggregate")]
    # ...and a tail fully below the halved threshold closes it
    hist = [0] * TELEM_LAG_BUCKETS
    hist[3] = 5
    hs.ingest(_sample(telem=_tel(3, hist=hist)))
    assert not hs.open_events()
    assert hs.recovery_stats()["apply_lag"]["n"] == 1


def test_unit_busy_rows_counter_and_degraded_flag():
    reg = MetricsRegistry()
    hs = HealthSampler(nh=None, registry=reg)
    hs.ingest(_sample({1: {"committed": 5, "leader_id": 1}}))
    assert hs.busy_rows_total == 0
    assert hs.report()["sampler_degraded"] is False
    hs.ingest(_sample({
        1: {"committed": 5, "leader_id": 1},
        2: {"busy": True},
        3: {"busy": True},
    }))
    assert hs.busy_rows_total == 2
    rep = hs.report()
    assert rep["sampler_degraded"] is True and rep["busy_rows"] == 2
    assert reg.counter_value("dragonboat_health_busy_rows_total") == 2
    # a clean pass clears the degradation flag; the counter is cumulative
    hs.ingest(_sample({1: {"committed": 6, "leader_id": 1}}))
    assert hs.report()["sampler_degraded"] is False
    assert hs.busy_rows_total == 2


def test_unit_busy_gap_keeps_detector_hysteresis():
    """A raft_mu-budget ``busy`` row mid-walk is a measurement gap, not
    evidence: an open detector must stay open across it, and no
    detector may open FROM a busy row (satellite 3)."""
    hs = _unit_sampler(apply_lag_entries=100)
    g = {"committed": 1000, "applied": 850, "leader_id": 1}
    hs.ingest(_sample({7: dict(g)}))
    assert _open_keys(hs) == [("apply_lag", "group:7")]
    # busy gap: the walk reached the group but the lock budget was spent
    for _ in range(3):
        hs.ingest(_sample({7: {"busy": True}}))
    assert _open_keys(hs) == [("apply_lag", "group:7")]
    # a busy row never OPENS anything either, whatever junk it carries
    hs.ingest(_sample({7: {"busy": True, "committed": 0, "applied": -999}}))
    assert _open_keys(hs) == [("apply_lag", "group:7")]
    # the next clean sample resumes the hysteresis exactly where it was
    hs.ingest(_sample({7: {"committed": 1000, "applied": 990,
                           "leader_id": 1}}))
    assert not hs.open_events()
    assert hs.recovery_stats()["apply_lag"]["n"] == 1


def test_unit_aggregate_walk_absence_is_not_gone():
    """Aggregate samples walk only the drill-down set: a group absent
    from the walk must keep its open event and per-group memory; only
    membership-resolved ``gone_cids`` close as removed."""
    hs = _unit_sampler(aggregate=True, apply_lag_entries=100)
    g7 = {"committed": 1000, "applied": 850, "leader_id": 1}
    hs.ingest(_sample({7: dict(g7)}, telem=_tel(1)))
    assert _open_keys(hs) == [("apply_lag", "group:7")]
    # 7 churns out of the top-K -> out of the walk.  NOT gone.
    hs.ingest(_sample({3: {"committed": 2, "leader_id": 1}},
                      telem=_tel(2)))
    assert _open_keys(hs) == [("apply_lag", "group:7")]
    assert 7 in hs._prev
    # true removal arrives via gone_cids -> closes and forgets
    hs.ingest(_sample({3: {"committed": 3, "leader_id": 1}},
                      telem=_tel(3), gone=[7]))
    assert not hs.open_events()
    assert 7 not in hs._prev


def test_unit_aggregate_publishes_telem_families():
    r = MetricsRegistry()
    hs = HealthSampler(nh=None, registry=r, aggregate=True)
    hist = [0] * TELEM_LAG_BUCKETS
    hist[0], hist[3] = 4, 1
    hs.ingest(_sample(telem=_tel(
        1, stalled=1, hist=hist, topk=[(12, 6)], states=[3, 0, 2, 0, 0],
    )))
    assert r.counter_value("dragonboat_telem_folds_total") == 1
    assert r.gauge_value("dragonboat_telem_stalled_groups") == 1
    assert r.gauge_value("dragonboat_telem_worst_lag") == 6
    assert r.gauge_value(
        "dragonboat_telem_groups", labels={"state": "follower"}
    ) == 3
    assert r.gauge_value(
        "dragonboat_telem_groups", labels={"state": "leader"}
    ) == 2
    assert r.gauge_value(
        "dragonboat_telem_commit_lag", labels={"bucket": "4"}
    ) == 1
    # a stale re-serve publishes nothing new
    hs.ingest(_sample(telem=_tel(1, stalled=0)))
    assert r.counter_value("dragonboat_telem_folds_total") == 1
    assert r.gauge_value("dragonboat_telem_stalled_groups") == 1


# ----------------------------------------------------------------------
# exposition streaming + cardinality guard (unit)
# ----------------------------------------------------------------------


def test_iter_health_metrics_matches_monolithic_writer():
    reg = MetricsRegistry()
    reg.counter_add("dragonboat_test_total", 3)
    reg.gauge_set("dragonboat_test_gauge", 1.5, labels={"shard": "0"})
    reg.gauge_set("dragonboat_test_gauge", 2.5, labels={"shard": "1"})
    reg.histogram_observe("dragonboat_test_seconds", 0.02)
    buf = io.StringIO()
    reg.write_health_metrics(buf)
    chunks = list(reg.iter_health_metrics())
    assert "".join(chunks) == buf.getvalue()
    # one chunk per family, each self-contained with its own preamble
    assert len(chunks) == 3
    for c in chunks:
        assert c.startswith("# HELP ") and "# TYPE " in c


def test_cardinality_guard_warns_once_per_family(caplog):
    reg = MetricsRegistry()
    reg.cardinality_warn = 5
    for i in range(8):
        reg.counter_add("dragonboat_leaky_total", labels={"req": str(i)})
    with caplog.at_level(logging.WARNING):
        list(reg.iter_health_metrics())
        list(reg.iter_health_metrics())  # second scrape: no re-warn
    warns = [r for r in caplog.records if "dragonboat_leaky_total" in r.getMessage()]
    assert len(warns) == 1
    assert "8 label sets" in warns[0].getMessage()
    # reset() re-arms the guard with the rest of the instrument state
    reg.reset()
    assert not reg._cardinality_warned


# ----------------------------------------------------------------------
# live NodeHost: aggregate mode end to end
# ----------------------------------------------------------------------


class CounterSM:
    def __init__(self, cluster_id, node_id):
        self.count = 0

    def update(self, cmd):
        self.count += 1
        return Result(value=self.count)

    def lookup(self, query):
        return self.count

    def save_snapshot(self, w, files, done):
        w.write(self.count.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        self.count = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def _mk_host(health_ms=20, engine="tpu", aggregate=True,
             metrics_addr="127.0.0.1:0"):
    return NodeHost(
        NodeHostConfig(
            node_host_dir=":memory:",
            rtt_millisecond=RTT_MS,
            raft_address="tl:1",
            raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                s, rh, ch, router=ChanRouter()
            ),
            enable_metrics=True,
            health_sample_ms=health_ms,
            health_aggregate=aggregate,
            metrics_addr=metrics_addr,
            expert=ExpertConfig(
                quorum_engine=engine,
                engine_block_groups=64,
                engine_warm_fused=False,
            ),
        )
    )


def _start(nh, cid=CID):
    nh.start_cluster(
        {1: nh.raft_address()}, False, CounterSM,
        Config(cluster_id=cid, node_id=1, election_rtt=10,
               heartbeat_rtt=1),
    )
    wait_until(
        lambda: nh.get_leader_id(cid)[1], timeout=10.0, what="leader"
    )


def _get(port, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    )


def test_live_aggregate_sampling_and_debug_telem():
    nh = _mk_host()
    try:
        _start(nh)
        assert nh.health is not None and nh.health.aggregate
        s = nh.get_noop_session(CID)
        for _ in range(3):
            assert nh.sync_propose(s, b"x", timeout=10.0)
        # folds flow: the sampler publishes them and samples go aggregate
        wait_until(
            lambda: nh.metrics_registry.counter_value(
                "dragonboat_telem_folds_total") > 0,
            timeout=10.0, what="telem fold",
        )
        wait_until(
            lambda: any(
                smp.get("aggregate") for smp in nh.health.samples()
            ),
            timeout=10.0, what="aggregate sample",
        )
        rep = nh.health_report()
        assert rep["aggregate"] is True
        assert rep["sampler_degraded"] is False
        # the drill-down walk still reaches the device group (top-K)
        agg = [smp for smp in nh.health.samples() if smp.get("aggregate")]
        assert any(CID in smp["groups"] for smp in agg)
        tel = agg[-1]["telem"]
        assert tel["groups"] == 1 and sum(tel["lag_hist"]) == 1
        # /debug/telem serves the live snapshot
        port = nh.metrics_server.port
        r = _get(port, "/debug/telem")
        assert r.status == 200
        body = json.loads(r.read())
        assert body["enabled"] is True
        assert body["snapshot"]["groups"] == 1
        assert len(body["snapshot"]["lag_hist"]) == TELEM_LAG_BUCKETS
    finally:
        nh.stop()


def test_debug_telem_404_when_off():
    nh = _mk_host(engine="scalar", aggregate=False)
    try:
        _start(nh)
        port = nh.metrics_server.port
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(port, "/debug/telem")
        assert ei.value.code == 404
    finally:
        nh.stop()


def test_metrics_streams_chunked_and_matches_writer():
    nh = _mk_host(engine="scalar", aggregate=False)
    try:
        _start(nh)
        s = nh.get_noop_session(CID)
        for _ in range(3):
            nh.sync_propose(s, b"x", timeout=10.0)
        wait_until(lambda: len(nh.health) >= 2, timeout=10.0,
                   what="samples")
        port = nh.metrics_server.port
        # raw HTTP/1.1 exchange: the endpoint must stream chunked
        with socket.create_connection(("127.0.0.1", port), timeout=10) as sk:
            sk.sendall(
                b"GET /metrics HTTP/1.1\r\nHost: t\r\n"
                b"Connection: close\r\n\r\n"
            )
            raw = b""
            while True:
                b_ = sk.recv(65536)
                if not b_:
                    break
                raw += b_
        head, _, payload = raw.partition(b"\r\n\r\n")
        headers = head.decode().lower()
        assert "transfer-encoding: chunked" in headers
        assert "content-length" not in headers
        # de-chunk and compare against the monolithic writer's families
        body = b""
        while payload:
            size, _, payload = payload.partition(b"\r\n")
            n = int(size, 16)
            if n == 0:
                break
            body += payload[:n]
            payload = payload[n + 2:]
        text = body.decode()
        buf = io.StringIO()
        nh.metrics_registry.write_health_metrics(buf)
        # same families and preamble structure (values may tick between
        # the two scrapes; names and HELP/TYPE lines are stable)
        chunk_names = {
            ln.split()[2] for ln in text.splitlines()
            if ln.startswith("# HELP")
        }
        mono_names = {
            ln.split()[2] for ln in buf.getvalue().splitlines()
            if ln.startswith("# HELP")
        }
        assert chunk_names == mono_names
        lines = text.splitlines()
        for i, line in enumerate(lines):
            if line.startswith("# TYPE"):
                name = line.split()[2]
                assert i > 0 and lines[i - 1].startswith(
                    f"# HELP {name} "
                ), line
        # an HTTP/1.0 scraper still gets the buffered form
        with socket.create_connection(("127.0.0.1", port), timeout=10) as sk:
            sk.sendall(b"GET /metrics HTTP/1.0\r\n\r\n")
            raw = b""
            while True:
                b_ = sk.recv(65536)
                if not b_:
                    break
                raw += b_
        head, _, payload10 = raw.partition(b"\r\n\r\n")
        assert b"content-length" in head.lower()
        assert b"dragonboat_health_samples_total" in payload10
    finally:
        nh.stop()
