"""Mutual-TLS transport + native fast lane.

The reference runs mutual TLS on every plane
(``/root/reference/internal/transport/tcp.go:582-595``).  Here the TLS
termination stays in Python on both directions — inbound: the TCP accept
thread decrypts and feeds plaintext to the native frame reassembler via
the stream hooks; outbound: the Python per-remote sender drains the
native send queue onto a TLS connection — so the fast lane's frames ride
the same encrypted channel as the scalar path and enrollment works with
no plaintext downgrade (round-4 VERDICT: the fd-takeover fast plane was
plain-TCP only).

Certificates are generated per-session with the openssl CLI (the
reference ships static localhost certs; generating keeps no key material
in the repo).
"""
import os
import socket
import ssl
import subprocess
import time

import pytest

from dragonboat_tpu import Config, NodeHostConfig, Result
from dragonboat_tpu.config import ExpertConfig, LogDBConfig
from dragonboat_tpu.nodehost import NodeHost

RTT_MS = 20


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    import shutil

    if shutil.which("openssl") is None:
        pytest.skip("openssl CLI not available")
    d = tmp_path_factory.mktemp("tls")
    ca_key, ca_crt = d / "ca.key", d / "ca.crt"
    key, csr, crt = d / "node.key", d / "node.csr", d / "node.crt"
    ext = d / "ext.cnf"
    run = lambda *a: subprocess.run(a, check=True, capture_output=True)
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
        "-subj", "/CN=dbtpu-test-ca")
    run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(key), "-out", str(csr), "-subj", "/CN=127.0.0.1")
    ext.write_text("subjectAltName=IP:127.0.0.1,DNS:localhost\n")
    run("openssl", "x509", "-req", "-in", str(csr), "-CA", str(ca_crt),
        "-CAkey", str(ca_key), "-CAcreateserial", "-out", str(crt),
        "-days", "1", "-extfile", str(ext))
    return str(ca_crt), str(crt), str(key)


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


class CounterSM:
    def __init__(self, cluster_id, node_id):
        self.v = 0

    def update(self, cmd):
        self.v += 1
        return Result(value=self.v)

    def lookup(self, q):
        return self.v

    def save_snapshot(self, w, files, done):
        w.write(self.v.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        self.v = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def _mk_nh(tmp, i, addr, addrs, certs, fast_lane):
    ca, crt, key = certs
    ldb = LogDBConfig()
    ldb.fsync = False  # cut fsync latency; TLS is what's under test
    return NodeHost(NodeHostConfig(
        node_host_dir=os.path.join(tmp, f"nh{i}"),
        rtt_millisecond=RTT_MS,
        raft_address=addr,
        mutual_tls=True, ca_file=ca, cert_file=crt, key_file=key,
        logdb_config=ldb,
        expert=ExpertConfig(
            quorum_engine="scalar", fast_lane=fast_lane, logdb_shards=2,
        ),
    ))


def test_mutual_tls_fast_lane_enrolls_and_replicates(tmp_path, certs):
    ports = _free_ports(3)
    addrs = {i + 1: f"127.0.0.1:{ports[i]}" for i in range(3)}
    nhs = []
    CID = 31
    try:
        for i in (1, 2, 3):
            nh = _mk_nh(str(tmp_path), i, addrs[i], addrs, certs,
                        fast_lane=True)
            nhs.append(nh)
            nh.start_cluster(addrs, False, CounterSM, Config(
                cluster_id=CID, node_id=i, election_rtt=10, heartbeat_rtt=1,
            ))
        # elect
        deadline = time.time() + 30
        leader = None
        while leader is None and time.time() < deadline:
            for nh in nhs:
                lid, ok = nh.get_leader_id(CID)
                if ok:
                    leader = nhs[lid - 1]
                    break
            time.sleep(0.02)
        assert leader is not None, "no leader over mutual TLS"
        # the fast lane must ENROLL under TLS (round-4: it could not)
        deadline = time.time() + 20
        while time.time() < deadline and not leader.get_node(CID).fast_lane:
            time.sleep(0.05)
        assert leader.get_node(CID).fast_lane, "no enrollment under TLS"
        # traffic flows natively over the encrypted channel
        s = leader.get_noop_session(CID)
        for k in range(50):
            r = leader.sync_propose(s, b"x", timeout=15.0)
            assert r.value == k + 1
        st = leader.fastlane.stats()
        assert st["proposed"] >= 40, f"native lane idle under TLS: {st}"
        # every replica applied (read through a follower's SM)
        deadline = time.time() + 15
        follower = next(nh for nh in nhs if nh is not leader)
        while time.time() < deadline and follower.stale_read(CID, None) < 50:
            time.sleep(0.05)
        assert follower.stale_read(CID, None) == 50
    finally:
        for nh in nhs:
            nh.stop()


def test_plaintext_client_rejected_by_tls_listener(tmp_path, certs):
    ports = _free_ports(1)
    addr = f"127.0.0.1:{ports[0]}"
    nh = _mk_nh(str(tmp_path), 9, addr, {1: addr}, certs, fast_lane=False)
    try:
        nh.start_cluster({1: addr}, False, CounterSM, Config(
            cluster_id=32, node_id=1, election_rtt=10, heartbeat_rtt=1,
        ))
        # a plaintext client must not get a usable channel
        s = socket.create_connection(("127.0.0.1", ports[0]), timeout=5)
        try:
            got = b""
            try:
                s.sendall(b"\xae\x7dGARBAGE-NOT-TLS" * 4)
                s.settimeout(5)
                while True:
                    b = s.recv(4096)
                    if not b:
                        break
                    got += b
            except (socket.timeout, ConnectionError, OSError):
                pass  # connection reset = rejection, the expected outcome
            # server either closes outright or answers only with a TLS
            # alert (0x15); it must never speak the raft framing protocol
            assert not got.startswith(b"\xae\x7d"), "plaintext accepted!"
        finally:
            s.close()
    finally:
        nh.stop()


def test_wrong_ca_client_rejected(tmp_path, tmp_path_factory, certs):
    """A client presenting a cert from a DIFFERENT CA fails the mutual
    handshake (verify_mode=CERT_REQUIRED on the server)."""
    d = tmp_path_factory.mktemp("tls2")
    run = lambda *a: subprocess.run(a, check=True, capture_output=True)
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(d / "other.key"), "-out", str(d / "other.crt"),
        "-days", "1", "-subj", "/CN=other-ca")
    ports = _free_ports(1)
    addr = f"127.0.0.1:{ports[0]}"
    nh = _mk_nh(str(tmp_path), 8, addr, {1: addr}, certs, fast_lane=False)
    try:
        nh.start_cluster({1: addr}, False, CounterSM, Config(
            cluster_id=33, node_id=1, election_rtt=10, heartbeat_rtt=1,
        ))
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        ctx.load_cert_chain(str(d / "other.crt"), str(d / "other.key"))
        raw = socket.create_connection(("127.0.0.1", ports[0]), timeout=5)
        # the server's bad_certificate rejection can surface as an SSL
        # alert or (timing-dependent, esp. TLS 1.3) a plain reset
        with pytest.raises((ssl.SSLError, ConnectionError)):
            tls = ctx.wrap_socket(raw, server_hostname="127.0.0.1")
            # some stacks surface the server's reject on first IO
            tls.sendall(b"\xae\x7d")
            tls.recv(1)
        raw.close()
    finally:
        nh.stop()
