"""Tools tests: ImportSnapshot quorum repair + checkdisk probe.

Reference model: ``tools/import.go`` (+ its tests) and
``tools/checkdisk/main.go``.
"""
import time

import pytest

from dragonboat_tpu import Config, NodeHostConfig, Result
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine
from dragonboat_tpu.tools import import_snapshot
from dragonboat_tpu.transport import ChanRouter, ChanTransport

RTT_MS = 5


class KVSM(IStateMachine):
    def __init__(self, cluster_id, node_id):
        self.kv = {}
        self.count = 0

    def update(self, cmd):
        k, v = cmd.decode().split("=", 1)
        self.kv[k] = v
        self.count += 1
        return Result(value=self.count)

    def lookup(self, query):
        return self.kv.get(query)

    def save_snapshot(self, w, files, done):
        data = repr(sorted(self.kv.items())).encode()
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, files, done):
        import ast

        n = int.from_bytes(r.read(8), "little")
        self.kv = dict(ast.literal_eval(r.read(n).decode()))
        self.count = len(self.kv)

    def close(self):
        pass


def _mk_nh(addr, router, tmpdir):
    return NodeHost(
        NodeHostConfig(
            node_host_dir=str(tmpdir),
            rtt_millisecond=RTT_MS,
            raft_address=addr,
            raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                src, rh, ch, router=router
            ),
        )
    )


def _wait_leader(nh, cid, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        _, ok = nh.get_leader_id(cid)
        if ok:
            return
        time.sleep(0.01)
    raise TimeoutError("no leader")


def test_import_snapshot_quorum_repair(tmp_path):
    """Full disaster-recovery round trip: run a group, export a snapshot,
    destroy the NodeHost dir (quorum loss), import into a fresh dir with a
    single-member map, restart, and read the old data back."""
    router = ChanRouter()
    cid = 7
    addr = "orig:1"
    nh = _mk_nh(addr, router, tmp_path / "orig")
    export_dir = tmp_path / "export"
    export_dir.mkdir()
    try:
        nh.start_cluster(
            {1: addr}, False, KVSM,
            Config(cluster_id=cid, node_id=1, election_rtt=10, heartbeat_rtt=1),
        )
        _wait_leader(nh, cid)
        s = nh.get_noop_session(cid)
        for i in range(8):
            nh.sync_propose(s, f"k{i}=v{i}".encode(), timeout=5.0)
        rs = nh.request_snapshot(cid, export_path=str(export_dir), timeout=5.0)
        r = rs.wait(5.0)
        idx = r.snapshot_index
        assert idx > 0
    finally:
        nh.stop()

    # the exported image lives in export_dir/snapshot-XXXX/
    from dragonboat_tpu.server.snapshotenv import snapshot_dir_name

    src = export_dir / snapshot_dir_name(idx)
    assert src.is_dir()

    # quorum lost: bring up a REPLACEMENT host at a new address/dir,
    # membership shrunk to just it
    new_addr = "repair:1"
    new_dir = tmp_path / "repair"
    nhc = NodeHostConfig(
        node_host_dir=str(new_dir),
        rtt_millisecond=RTT_MS,
        raft_address=new_addr,
        raft_rpc_factory=lambda src_, rh, ch: ChanTransport(
            src_, rh, ch, router=router
        ),
    )
    members = {1: new_addr}
    ss = import_snapshot(nhc, str(src), members, 1)
    assert ss.imported and ss.index == idx
    assert ss.membership.addresses == members
    assert ss.membership.config_change_id == idx

    nh2 = NodeHost(nhc)
    try:
        nh2.start_cluster(members, False, KVSM, Config(
            cluster_id=cid, node_id=1, election_rtt=10, heartbeat_rtt=1,
        ))
        _wait_leader(nh2, cid)
        for i in range(8):
            assert nh2.sync_read(cid, f"k{i}", timeout=5.0) == f"v{i}"
        # and the repaired group accepts new writes
        s = nh2.get_noop_session(cid)
        nh2.sync_propose(s, b"new=1", timeout=5.0)
        assert nh2.sync_read(cid, "new", timeout=5.0) == "1"
    finally:
        nh2.stop()


def test_import_snapshot_validations(tmp_path):
    nhc = NodeHostConfig(
        node_host_dir=str(tmp_path),
        raft_address="a:1",
    )
    with pytest.raises(ValueError, match="not in the new membership"):
        import_snapshot(nhc, str(tmp_path), {2: "b:1"}, 1)
    with pytest.raises(ValueError, match="address"):
        import_snapshot(nhc, str(tmp_path), {1: "wrong:1"}, 1)
    with pytest.raises(ValueError, match="no exported snapshot"):
        import_snapshot(nhc, str(tmp_path), {1: "a:1"}, 1)


def test_checkdisk_probe_runs():
    from dragonboat_tpu.tools.checkdisk import run

    out = run(groups=4, seconds=1.0, payload=16, client_threads=2)
    assert out["metric"] == "checkdisk_writes_per_sec"
    assert out["writes"] > 0
    assert out["errors"] == 0
