"""TPU quorum plugin integration tests.

The north-star plugin boundary (BASELINE.json): with
``ExpertConfig.quorum_engine="tpu"``, the live runtime's ack tallying,
commit advancement and vote tallying run through the batched device
engine; with "scalar" the pure-host path is untouched.  These tests run
real multi-replica clusters in both modes and require identical outcomes.
"""
import threading
import time

import pytest

from dragonboat_tpu import Config, NodeHostConfig, Result
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine
from dragonboat_tpu.transport import ChanRouter, ChanTransport

RTT_MS = 5
CID = 21


class KVSM(IStateMachine):
    def __init__(self, cluster_id, node_id):
        self.kv = {}
        self.count = 0

    def update(self, cmd):
        k, v = cmd.decode().split("=", 1)
        self.kv[k] = v
        self.count += 1
        return Result(value=self.count)

    def lookup(self, query):
        return self.kv.get(query)

    def save_snapshot(self, w, files, done):
        data = repr(sorted(self.kv.items())).encode()
        w.write(len(data).to_bytes(8, "little") + data)

    def recover_from_snapshot(self, r, files, done):
        import ast

        n = int.from_bytes(r.read(8), "little")
        self.kv = dict(ast.literal_eval(r.read(n).decode()))
        self.count = len(self.kv)

    def close(self):
        pass


def _mk_nh(addr, router, engine="tpu"):
    return NodeHost(
        NodeHostConfig(
            node_host_dir=":memory:",
            rtt_millisecond=RTT_MS,
            raft_address=addr,
            raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                src, rh, ch, router=router
            ),
            expert=ExpertConfig(quorum_engine=engine, engine_block_groups=64),
        )
    )


def _wait_leader(nhs, cid, timeout=15.0):
    # load-scaled AND re-sampled (the r14/r17 deflake discipline): a
    # budget priced once at entry underprices an election that starts
    # on a momentarily-idle box sharing it with a heavy neighbor
    # spinning up; the budget only ever GROWS toward
    # ``timeout * current_scale``
    from tests.loadwait import scale, scaled

    start = time.time()
    budget = scaled(timeout)
    while True:
        for nh in nhs:
            _, ok = nh.get_leader_id(cid)
            if ok:
                return
        budget = max(budget, timeout * scale())
        if time.time() - start >= budget:
            raise TimeoutError("no leader")
        time.sleep(0.01)


def _cluster(router, engine, n=3, prefix="tq"):
    addrs = {i: f"{prefix}{i}:1" for i in range(1, n + 1)}
    nhs = [_mk_nh(addrs[i], router, engine) for i in range(1, n + 1)]
    for i, nh in enumerate(nhs, start=1):
        nh.start_cluster(
            addrs, False, KVSM,
            Config(cluster_id=CID, node_id=i, election_rtt=10, heartbeat_rtt=1),
        )
    return nhs, addrs


def test_tpu_engine_propose_and_read():
    """3 replicas, device-tallied commits: propose/read round trip."""
    router = ChanRouter()
    nhs, _ = _cluster(router, "tpu")
    try:
        _wait_leader(nhs, CID)
        assert nhs[0].quorum_coordinator is not None
        s = nhs[0].get_noop_session(CID)
        for i in range(20):
            r = nhs[0].sync_propose(s, f"k{i}=v{i}".encode(), timeout=30.0)
            assert r.value == i + 1
        for i in range(20):
            assert nhs[0].sync_read(CID, f"k{i}", timeout=30.0) == f"v{i}"
        # the engine actually owns the group rows
        eng = nhs[0].quorum_coordinator.eng
        assert CID in eng.groups
    finally:
        for nh in nhs:
            nh.stop()


def test_tpu_engine_single_replica():
    router = ChanRouter()
    nh = _mk_nh("solo:1", router, "tpu")
    try:
        nh.start_cluster(
            {1: "solo:1"}, False, KVSM,
            Config(cluster_id=CID, node_id=1, election_rtt=10, heartbeat_rtt=1),
        )
        _wait_leader([nh], CID)
        s = nh.get_noop_session(CID)
        for i in range(5):
            nh.sync_propose(s, f"a{i}=1".encode(), timeout=30.0)
        assert nh.sync_read(CID, "a4", timeout=30.0) == "1"
    finally:
        nh.stop()


def test_tpu_engine_leader_failover():
    """Stop the leader; the device-tallied election elects a successor and
    writes continue."""
    router = ChanRouter()
    nhs, addrs = _cluster(router, "tpu", prefix="fo")
    try:
        _wait_leader(nhs, CID)
        from tests.loadwait import scale, scaled

        lid = 0
        start = time.time()
        budget = scaled(10.0)
        while not lid:
            for nh in nhs:
                l, ok = nh.get_leader_id(CID)
                if ok:
                    lid = l
                    break
            else:
                # re-sampled budget (r17 deflake): the one-shot deadline
                # underpriced entry-time-idle waits
                budget = max(budget, 10.0 * scale())
                if time.time() - start >= budget:
                    break
                time.sleep(0.05)
        assert lid
        leader_nh = nhs[lid - 1]
        leader_nh.stop_cluster(CID)
        survivors = [nh for nh in nhs if nh is not leader_nh]
        _wait_leader(survivors, CID)
        s = survivors[0].get_noop_session(CID)
        # deadline-based re-sampled retry, not a fixed attempt count:
        # 20 x 3s priced the post-failover re-election for an idle box
        committed = False
        start = time.time()
        budget = scaled(60.0)
        while not committed:
            try:
                survivors[0].sync_propose(
                    s, b"after=failover", timeout=scaled(3.0)
                )
                committed = True
            except Exception:
                budget = max(budget, 60.0 * scale())
                if time.time() - start >= budget:
                    break
                time.sleep(0.2)
        assert committed
        assert survivors[0].sync_read(CID, "after", timeout=30.0) == "failover"
    finally:
        for nh in nhs:
            nh.stop()


def _propose_retry(nh, s, data, timeout=30.0, attempts=3):
    """Noop-session propose with a load-scaled timeout and retry: under
    full-suite load one 4-host window can starve past a single timeout
    (the r07 contention-flake class) while the cluster is perfectly
    healthy; a noop-session duplicate is harmless for these asserts."""
    from dragonboat_tpu.requests import TimeoutError_
    from tests.loadwait import scaled

    for a in range(attempts):
        try:
            return nh.sync_propose(s, data, timeout=scaled(timeout))
        except TimeoutError_:
            if a == attempts - 1:
                raise


def _config_change_retry(nh, cid, request, pred, what, budget=90.0):
    """Drive a membership change under full-suite load (the r07
    contention-flake class): a single synchronous attempt couples one
    request tracker to one leadership term, and either can die of
    weather while the cluster is healthy — worse, the proposal may
    COMMIT after its ack timed out.  So the request is only the driver
    and membership is the verdict: re-issue within a load-scaled budget
    until ``pred(membership)`` holds (a duplicate attempt after a
    silent commit is rejected by the config-change guard, which the
    membership check absorbs; a PendingConfigChangeExistError means the
    timed-out attempt is STILL in flight on the node — also just keep
    polling, it may yet commit)."""
    from dragonboat_tpu.requests import (
        PendingConfigChangeExistError, RejectedError, TimeoutError_,
    )
    from tests.loadwait import scale, scaled

    start = time.time()
    # re-sampled while waiting (r17 deflake): the budget only ever
    # grows toward ``budget * current_scale``
    limit = scaled(budget)
    last = None
    while True:
        try:
            request(scaled(15.0))
            return
        except (TimeoutError_, RejectedError,
                PendingConfigChangeExistError) as e:
            last = e
        try:
            m = nh.sync_get_cluster_membership(cid, timeout=scaled(10.0))
        except TimeoutError_:
            m = None
        if m is not None and pred(m):
            return  # the "failed" attempt actually committed
        limit = max(limit, budget * scale())
        if time.time() - start >= limit:
            break
    raise AssertionError(
        f"{what} not achieved within {limit:.1f}s "
        f"(base {budget:.1f}s x load {scale():.2f}); last={last!r}"
    )


def _wait_membership(nh, cid, pred, timeout=15.0, what="membership"):
    """Poll membership until ``pred(m)`` holds, within a load-scaled
    budget (ISSUE 13 deflake): a single ``sync_get_cluster_membership``
    under full-suite load can time out while the cluster is healthy —
    the documented r07/r10/r12 membership-discovery flake — and its
    TimeoutError_ escaped the old polling loop as a verdict.  One
    failed attempt here is weather; the deadline decides."""
    from dragonboat_tpu.requests import TimeoutError_
    from tests.loadwait import scale, scaled

    start = time.time()
    # re-sampled while waiting (r17 deflake; see _config_change_retry)
    limit = scaled(timeout)
    last = None
    while True:
        try:
            last = nh.sync_get_cluster_membership(cid, timeout=scaled(10.0))
        except TimeoutError_:
            last = None
        if last is not None and pred(last):
            return last
        limit = max(limit, timeout * scale())
        if time.time() - start >= limit:
            break
        time.sleep(0.1)
    raise AssertionError(
        f"{what} not reached within {limit:.1f}s "
        f"(base {timeout:.1f}s x load {scale():.2f}); last={last}"
    )


def test_tpu_engine_membership_change():
    """Add a 4th member and remove it again with the device engine on —
    the row resync path."""
    router = ChanRouter()
    nhs, addrs = _cluster(router, "tpu", prefix="mc")
    nh4 = _mk_nh("mc4:1", router, "tpu")
    try:
        _wait_leader(nhs, CID)
        _config_change_retry(
            nhs[0], CID,
            lambda t: nhs[0].sync_request_add_node(CID, 4, "mc4:1",
                                                   timeout=t),
            lambda m: 4 in m.addresses, what="add node 4",
        )
        nh4.start_cluster(
            {}, True, KVSM,
            Config(cluster_id=CID, node_id=4, election_rtt=10, heartbeat_rtt=1),
        )
        s = nhs[0].get_noop_session(CID)
        for i in range(5):
            _propose_retry(nhs[0], s, f"m{i}=1".encode())
        _wait_membership(
            nhs[0], CID, lambda m: 4 in m.addresses, what="node 4 joined"
        )
        _config_change_retry(
            nhs[0], CID,
            lambda t: nhs[0].sync_request_delete_node(CID, 4, timeout=t),
            lambda m: 4 not in m.addresses, what="delete node 4",
        )
        for i in range(5):
            _propose_retry(nhs[0], s, f"n{i}=1".encode())
        _wait_membership(
            nhs[0], CID, lambda m: 4 not in m.addresses,
            what="node 4 removed",
        )
    finally:
        for nh in nhs + [nh4]:
            nh.stop()


def test_scalar_vs_tpu_differential():
    """Same workload in both modes: identical SM results and final state —
    the bit-identical commit discipline at the cluster level."""
    from tests.loadwait import scaled

    results = {}
    for engine in ("scalar", "tpu"):
        router = ChanRouter()
        nhs, _ = _cluster(router, engine, prefix=f"d{engine[:1]}")
        try:
            _wait_leader(nhs, CID)
            s = nhs[0].get_noop_session(CID)
            vals = []
            for i in range(30):
                # load-scaled TIMEOUT only, never a retry: a noop-session
                # duplicate would fork the scalar/tpu count sequences and
                # fail the differential on a healthy cluster
                r = nhs[0].sync_propose(
                    s, f"k{i % 7}=v{i}".encode(), scaled(30.0)
                )
                vals.append(r.value)
            reads = [
                nhs[0].sync_read(CID, f"k{j}", timeout=scaled(30.0))
                for j in range(7)
            ]
            results[engine] = (vals, reads)
        finally:
            for nh in nhs:
                nh.stop()
    assert results["scalar"] == results["tpu"], results


def test_tpu_engine_snapshot_and_restart(tmp_path):
    """Snapshot + restart with the plugin enabled (row re-registration on
    restart)."""
    router = ChanRouter()
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=str(tmp_path),
            rtt_millisecond=RTT_MS,
            raft_address="sr:1",
            raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                src, rh, ch, router=router
            ),
            expert=ExpertConfig(quorum_engine="tpu", engine_block_groups=64),
        )
    )
    try:
        nh.start_cluster(
            {1: "sr:1"}, False, KVSM,
            Config(cluster_id=CID, node_id=1, election_rtt=10, heartbeat_rtt=1),
        )
        _wait_leader([nh], CID)
        s = nh.get_noop_session(CID)
        for i in range(8):
            nh.sync_propose(s, f"k{i}=v{i}".encode(), timeout=30.0)
        assert nh.sync_request_snapshot(CID, timeout=30.0) > 0
    finally:
        nh.stop()
    nh2 = NodeHost(
        NodeHostConfig(
            node_host_dir=str(tmp_path),
            rtt_millisecond=RTT_MS,
            raft_address="sr:1",
            raft_rpc_factory=lambda src, rh, ch: ChanTransport(
                src, rh, ch, router=ChanRouter()
            ),
            expert=ExpertConfig(quorum_engine="tpu", engine_block_groups=64),
        )
    )
    try:
        nh2.start_cluster(
            {}, False, KVSM,
            Config(cluster_id=CID, node_id=1, election_rtt=10, heartbeat_rtt=1),
        )
        _wait_leader([nh2], CID)
        for i in range(8):
            assert nh2.sync_read(CID, f"k{i}", timeout=30.0) == f"v{i}"
        s = nh2.get_noop_session(CID)
        nh2.sync_propose(s, b"post=restart", timeout=30.0)
        assert nh2.sync_read(CID, "post", timeout=30.0) == "restart"
    finally:
        nh2.stop()
