"""Cross-plane request tracing differential suite (ISSUE 9).

Contracts under test:

- trace-OFF structural identity: with ``trace_sample_every=0`` nothing
  is constructed — no tracer on the NodeHost/engine/node/coordinator,
  ``RequestState.trace`` stays None — with ``host_compartments`` both
  off and on;
- trace completeness: every sampled proposal's completed trace carries
  the full stage chain (propose → ingress → raft_step → wal → apply →
  egress, plus device_round on the tpu engine), including proposals
  committed by a FUSED K-batched round (linked recorder span is the
  fused dispatch) and proposals interleaved with a membership change
  (engine row recycle) mid-trace;
- the stage-level stall watchdog: a sampled request stuck in a stage by
  an injected WAL fsync failure (vfs.ErrorFS) auto-dumps its partial
  trace — plus the flight-recorder ring when one is attached;
- the Perfetto/Chrome export renders one request as ONE flow (s/t/f
  events sharing the trace id) across the stage slices.
"""
import json
import time

from dragonboat_tpu import Config, NodeHostConfig, Result
from dragonboat_tpu import vfs
from dragonboat_tpu.config import ExpertConfig
from dragonboat_tpu.logdb import open_logdb
from dragonboat_tpu.logdb.kv import WalKV
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.obs import FlightRecorder
from dragonboat_tpu.obs.trace import Trace, Tracer
from dragonboat_tpu.events import MetricsRegistry
from dragonboat_tpu.requests import RequestState
from dragonboat_tpu.transport import ChanRouter, ChanTransport

from tests.loadwait import wait_until

RTT_MS = 5
CID = 910

WRITE_STAGES = {"ingress", "raft_step", "wal", "apply", "egress"}


class CounterSM:
    def __init__(self, cluster_id, node_id):
        self.count = 0

    def update(self, cmd):
        self.count += 1
        return Result(value=self.count)

    def lookup(self, query):
        return self.count

    def save_snapshot(self, w, files, done):
        w.write(self.count.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        self.count = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def _mk_host(addr="tr:1", trace=0, engine="scalar", compartments=False,
             metrics=False, tmpdir=None, logdb_factory=None, fs=None,
             warm_fused=True):
    router = ChanRouter()
    return NodeHost(
        NodeHostConfig(
            node_host_dir=tmpdir or ":memory:",
            rtt_millisecond=RTT_MS,
            raft_address=addr,
            raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                s, rh, ch, router=router
            ),
            enable_metrics=metrics,
            trace_sample_every=trace,
            logdb_factory=logdb_factory,
            expert=ExpertConfig(
                quorum_engine=engine,
                engine_block_groups=64,
                engine_warm_fused=warm_fused,
                host_compartments=compartments,
                fs=fs,
            ),
        )
    )


def _start(nh, cid=CID):
    nh.start_cluster(
        {1: nh.raft_address()}, False, CounterSM,
        Config(cluster_id=cid, node_id=1, election_rtt=10, heartbeat_rtt=1),
    )
    wait_until(
        lambda: nh.get_leader_id(cid)[1], timeout=10.0, what="leader"
    )


def _stages(trace):
    return {e["stage"] for e in trace.to_dict()["events"]}


# ----------------------------------------------------------------------
# trace OFF: structural identity (compartments off AND on)
# ----------------------------------------------------------------------


def _assert_trace_off(nh):
    assert nh.tracer is None
    assert nh.engine.tracer is None
    node = nh.get_node(CID)
    assert node.tracer is None
    assert node.pending_reads._tracer is None
    if nh.quorum_coordinator is not None:
        assert nh.quorum_coordinator.tracer is None
    s = nh.get_noop_session(CID)
    states = [nh.propose(s, b"x", timeout=10.0)]
    states += nh.propose_batch(s, [b"y", b"y"], timeout=10.0)
    rrs = node.read(10.0)
    for rs in states + [rrs]:
        assert rs.wait(10.0).completed
        assert rs.trace is None  # the bit-identical latch


def test_trace_off_identity_compartments_off():
    nh = _mk_host(trace=0, compartments=False)
    try:
        _start(nh)
        _assert_trace_off(nh)
    finally:
        nh.stop()


def test_trace_off_identity_compartments_on():
    nh = _mk_host(trace=0, compartments=True)
    try:
        _start(nh)
        _assert_trace_off(nh)
    finally:
        nh.stop()


# ----------------------------------------------------------------------
# completeness: every sampled proposal carries the full stage chain
# ----------------------------------------------------------------------


def _drive_and_collect(nh, n=6):
    s = nh.get_noop_session(CID)
    states = [nh.propose(s, b"w", timeout=10.0) for _ in range(n // 2)]
    states += nh.propose_batch(s, [b"b"] * (n - n // 2), timeout=10.0)
    for rs in states:
        assert rs.wait(10.0).completed
    # egress stamps land inside notify, before wait() returns; finish()
    # moved each trace to the completed ring synchronously
    return [rs.trace for rs in states]


def test_completeness_scalar_engine():
    nh = _mk_host(trace=1)
    try:
        _start(nh)
        traces = _drive_and_collect(nh)
        for t in traces:
            assert type(t) is Trace and t.done
            assert _stages(t) >= WRITE_STAGES, t.to_dict()
        # reads: ingress -> raft_step -> read_confirm -> apply -> egress
        rrs = nh.get_node(CID).read(10.0)
        assert rrs.wait(10.0).completed
        assert _stages(rrs.trace) >= {
            "ingress", "read_confirm", "apply", "egress"
        }, rrs.trace.to_dict()
        # stage histograms published per stage
        reg = nh.metrics_registry
        for stage in WRITE_STAGES:
            h = reg.histogram_value(
                "dragonboat_trace_stage_seconds", {"stage": stage}
            )
            assert h is not None and h[3] > 0, stage
        assert reg.histogram_value("dragonboat_trace_e2e_seconds")[3] > 0
    finally:
        nh.stop()


def test_system_busy_reject_discards_contexts():
    """Regression (code review): a full ingress ring raises SystemBusy
    AFTER contexts attach but before the futures reach any tracker — no
    notify will ever finish them, so the tracer must discard them or
    they leak in flight forever (and trip the stall watchdog)."""
    router = ChanRouter()
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=":memory:",
            rtt_millisecond=RTT_MS,
            raft_address="tr:1",
            raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                s, rh, ch, router=router
            ),
            trace_sample_every=1,
            expert=ExpertConfig(
                host_compartments=True, host_ingress_ring=4,
            ),
        )
    )
    try:
        _start(nh)
        from dragonboat_tpu.requests import SystemBusyError

        s = nh.get_noop_session(CID)
        ing = nh.hostplane.ingress
        ing.pause()
        staged = []
        try:
            import pytest

            with pytest.raises(SystemBusyError):
                for _ in range(64):
                    staged.extend(nh.propose_batch(s, [b"x"], timeout=10.0))
        finally:
            ing.resume()
        for rs in staged:
            assert rs.wait(10.0).completed
        wait_until(
            lambda: not nh.tracer.inflight(), timeout=10.0,
            what="discarded/completed trace contexts",
        )
        assert nh.tracer.check_stalls() == 0
    finally:
        nh.stop()


def test_completeness_lease_read_short_path():
    """ISSUE 10: a read served under a leader lease shows the SHORT path
    — a ``lease_read`` stage in place of ``read_confirm`` (no echo-quorum
    round ran) — while a lease-off replica on the same build keeps the
    confirmed chain."""
    router = ChanRouter()

    def mk(i, trace):
        return NodeHost(
            NodeHostConfig(
                node_host_dir=":memory:",
                rtt_millisecond=RTT_MS,
                raft_address=f"lr{i}:1",
                raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                    s, rh, ch, router=router
                ),
                trace_sample_every=trace,
                expert=ExpertConfig(quorum_engine="scalar"),
            )
        )

    nhs = [mk(i, 1 if i == 1 else 0) for i in (1, 2, 3)]
    try:
        addrs = {i: f"lr{i}:1" for i in (1, 2, 3)}
        for i, nh in enumerate(nhs, start=1):
            nh.start_cluster(
                addrs, False, CounterSM,
                Config(
                    cluster_id=CID, node_id=i, election_rtt=10,
                    heartbeat_rtt=1, check_quorum=True, read_lease=True,
                ),
            )
        node1 = nhs[0].get_node(CID)

        def _drive_leader1():
            if node1.is_leader():
                return True
            lid, ok = node1.get_leader_id()
            if ok and lid != 1 and 1 <= lid <= 3:
                try:
                    nhs[lid - 1].request_leader_transfer(CID, 1)
                except Exception:
                    pass
            else:
                node1.request_campaign()
            return False

        wait_until(
            _drive_leader1, timeout=20.0, interval=0.2,
            what="leader on host 1",
        )
        s = nhs[0].get_noop_session(CID)
        rs = nhs[0].propose(s, b"x", timeout=10.0)
        assert rs.wait(10.0).completed
        wait_until(
            lambda: (nhs[0].lease_status(CID) or {}).get("held"),
            timeout=10.0, what="lease armed",
        )
        rrs = node1.read(10.0)
        assert rrs.wait(10.0).completed
        stages = _stages(rrs.trace)
        assert stages >= {"ingress", "lease_read", "apply", "egress"}, (
            rrs.trace.to_dict()
        )
        assert "read_confirm" not in stages
        # the stage histogram carries the new stage label (observations
        # flush to the registry once per tick — wait one out)
        wait_until(
            lambda: (
                nhs[0].metrics_registry.histogram_value(
                    "dragonboat_trace_stage_seconds",
                    {"stage": "lease_read"},
                )
                or (None,) * 4
            )[3],
            timeout=10.0, what="lease_read stage histogram flushed",
        )
    finally:
        for nh in nhs:
            nh.stop()


def test_completeness_compartments_ingress_path():
    """The compartmentalized path: bursts ride the ingress ring, the WAL
    stage lands at the group-commit flusher — the same stage chain must
    close."""
    nh = _mk_host(trace=1, compartments=True)
    try:
        _start(nh)
        for t in _drive_and_collect(nh):
            assert _stages(t) >= WRITE_STAGES, t.to_dict()
    finally:
        nh.stop()


def test_completeness_tpu_engine_device_round_and_recycle():
    """tpu engine: writes additionally carry the device_round stage with
    a linked recorder span; a membership change (engine row recycle)
    mid-stream must not break trace completeness on either side."""
    nh = _mk_host(trace=1, engine="tpu", metrics=True, warm_fused=False)
    try:
        _start(nh)
        before = _drive_and_collect(nh)
        # membership recycle mid-trace: an observer add commits a config
        # change and resyncs the engine row (tpuquorum membership_changed)
        nh.sync_request_add_observer(CID, 9, "trobs:1", timeout=10.0)
        after = _drive_and_collect(nh)
        for t in before + after:
            assert _stages(t) >= WRITE_STAGES | {"device_round"}, (
                t.to_dict()
            )
            assert t.spans, "device_round must link a recorder span seq"
        rec = nh.flight_recorder
        seqs = {s["seq"] for s in rec.spans()}
        linked = {seq for t in before + after for seq in t.spans}
        # linked seqs are real recorder spans (the ring may have evicted
        # the oldest; at capacity 512 in this test it has not)
        assert linked <= seqs | set(range(min(seqs, default=0))), (
            linked, max(seqs, default=-1)
        )
    finally:
        nh.stop()


def test_fused_round_links_fused_span():
    """Proposals committed by a fused K-batched round: hold the round
    lock, stage a tick backlog plus writes, release — the backlog replays
    as ONE fused dispatch and the traces' linked span is that fused
    span."""
    nh = _mk_host(trace=1, engine="tpu", metrics=True, warm_fused=False)
    try:
        _start(nh)
        qc = nh.quorum_coordinator
        # warm just the K=4 bucket synchronously (the full background
        # warm set is the live default; one bucket keeps the test fast)
        qc.eng.warmup_fused(k_buckets=(4,), background=False)
        assert qc.eng.fused_ready
        s = nh.get_noop_session(CID)
        fused_before = qc.fused_dispatches
        with qc._mu:  # block the round thread mid-loop
            states = nh.propose_batch(s, [b"f"] * 4, timeout=10.0)
            time.sleep(0.05)  # let raft step + ack staging land
            for _ in range(4):  # tick backlog -> deficit > 1
                qc.request_tick()
        for rs in states:
            assert rs.wait(10.0).completed
        wait_until(
            lambda: qc.fused_dispatches > fused_before, timeout=10.0,
            what="a fused dispatch",
        )
        rec = nh.flight_recorder
        by_seq = {sp["seq"]: sp for sp in rec.spans()}
        fused_linked = [
            by_seq[seq]
            for rs in states
            for seq in rs.trace.spans
            if seq in by_seq and by_seq[seq]["kind"] == "fused"
        ]
        assert fused_linked, [rs.trace.to_dict() for rs in states]
        assert any(sp["rounds"] > 1 for sp in fused_linked)
    finally:
        nh.stop()


# ----------------------------------------------------------------------
# stall watchdog: injected WAL stall dumps the partial trace
# ----------------------------------------------------------------------


def test_watchdog_dumps_partial_trace_on_wal_stall(tmp_path):
    """vfs.ErrorFS fails every fsync: a sampled proposal wedges after
    raft_step (its WAL flush cycle keeps failing), and the stage-level
    watchdog — driven by the NodeHost tick worker — auto-dumps the
    partial trace naming the stuck stage."""
    failing = [False]
    inj = vfs.Injector(lambda op, path: failing[0] and op == "fsync")
    efs = vfs.ErrorFS(vfs.OSFS(), inj)
    ldb_dir = str(tmp_path / "wal")

    def logdb_factory(nhc):
        return open_logdb(
            ldb_dir, shards=2,
            kv_factory=lambda d: WalKV(d, fsync=True, fs=efs),
        )

    nh = _mk_host(
        trace=1, compartments=True, tmpdir=str(tmp_path / "nh"),
        logdb_factory=logdb_factory, fs=efs,
    )
    try:
        _start(nh)
        s = nh.get_noop_session(CID)
        assert nh.sync_propose(s, b"pre", timeout=10.0).value == 1
        nh.tracer.stall_ms = 50.0
        failing[0] = True
        rs = nh.propose(s, b"stuck", timeout=60.0)
        assert not rs.wait(0.5).completed
        wait_until(
            lambda: nh.tracer.last_stall_dump is not None, timeout=10.0,
            what="trace stall auto-dump (tick worker)",
        )
        dump = nh.tracer.last_stall_dump
        assert "trace-stall" in dump["reason"]
        stuck = dump["trace"]
        stages = [e["stage"] for e in stuck["events"]]
        assert "wal" not in stages and "apply" not in stages, stages
        assert stuck["stalled"] in ("ingress", "raft_step"), stuck
        assert not stuck["done"]
        assert nh.metrics_registry.counter_value(
            "dragonboat_trace_stalls_total"
        ) >= 1
        # heal: the committer retry lands it and the trace completes
        failing[0] = False
        assert rs.wait(10.0).completed
        assert rs.trace.done
    finally:
        nh.stop()


def test_tracer_stall_dump_includes_recorder_ring():
    """Unit-level: when a FlightRecorder is attached the stall dump
    carries the recorder ring next to the partial trace."""
    rec = FlightRecorder(capacity=8, stall_ms=0)
    rec.record("dispatch", gate="acks", rounds=1)
    tr = Tracer(sample_every=1, registry=MetricsRegistry(), recorder=rec,
                stall_ms=5.0)
    try:
        rs = RequestState(key=77)
        tr.attach_one(rs, 3, time.perf_counter())
        tr.mark(rs, "ingress")
        time.sleep(0.02)
        assert tr.check_stalls() == 1
        d = tr.last_stall_dump
        assert d["trace"]["stalled"] == "ingress"
        assert d["recorder"]["spans"][0]["kind"] == "dispatch"
        # trips at most once per trace
        assert tr.check_stalls() == 0
    finally:
        tr.close()


# ----------------------------------------------------------------------
# export + debug dump
# ----------------------------------------------------------------------


def test_dump_trace_one_flow_per_request(tmp_path):
    """Acceptance: the exported Perfetto/Chrome trace renders a sampled
    proposal as one flow — ingress, WAL, device-round, apply and egress
    slices bound by s/t/f flow events sharing the trace id, with linked
    recorder spans on the device-plane track."""
    nh = _mk_host(trace=1, engine="tpu", metrics=True, warm_fused=False)
    try:
        _start(nh)
        traces = _drive_and_collect(nh, n=2)
        path = str(tmp_path / "trace.json")
        d = nh.dump_trace(path=path)
        with open(path) as f:
            assert json.load(f)["traceEvents"]  # valid JSON on disk
        evs = d["traceEvents"]
        tid = traces[0].tid
        slices = [
            e for e in evs
            if e["ph"] == "X" and e.get("args", {}).get("trace_id") == tid
        ]
        names = {e["name"] for e in slices}
        assert names >= WRITE_STAGES | {"device_round"}, names
        flow = [e for e in evs if e["ph"] in "stf" and e.get("id") == tid]
        phs = [e["ph"] for e in flow]
        assert phs[0] == "s" and phs[-1] == "f" and len(flow) >= 3
        # thread metadata names every tid used
        tids_used = {e["tid"] for e in slices}
        named = {
            e["tid"] for e in evs
            if e["ph"] == "M" and e["name"] == "thread_name"
        }
        assert tids_used <= named
        # the device-plane track carries the linked recorder spans
        dev = [e for e in evs if e.get("cat") == "device"]
        assert dev and {s["args"]["seq"] for s in dev} >= set(
            traces[0].spans
        )
    finally:
        nh.stop()


def test_debug_dump_writes_recorder_and_traces(tmp_path):
    nh = _mk_host(trace=1, engine="tpu", metrics=True, warm_fused=False)
    try:
        _start(nh)
        _drive_and_collect(nh, n=2)
        path = nh.debug_dump(path=str(tmp_path / "dump.json"))
        with open(path) as f:
            d = json.load(f)
        assert d["recorder"]["spans"]
        assert d["traces"]["completed"] >= 2
        assert d["traces"]["traces"][0]["events"]
    finally:
        nh.stop()


def test_sigusr2_handler_dumps(tmp_path):
    """Opt-in SIGUSR2: raising the signal writes a timestamped dump file
    (and the old handler is restored at stop)."""
    import glob
    import os
    import signal

    old = signal.getsignal(signal.SIGUSR2)
    nh = NodeHost(
        NodeHostConfig(
            node_host_dir=str(tmp_path / "nh"),
            rtt_millisecond=RTT_MS,
            raft_address="sig:1",
            raft_rpc_factory=lambda s, rh, ch: ChanTransport(
                s, rh, ch, router=ChanRouter()
            ),
            trace_sample_every=1,
            dump_signal=True,
        )
    )
    try:
        assert nh._dump_sig_old is not None or (
            signal.getsignal(signal.SIGUSR2) is old
        )
        _start(nh)
        s = nh.get_noop_session(CID)
        nh.sync_propose(s, b"x", timeout=10.0)
        os.kill(os.getpid(), signal.SIGUSR2)
        # the handler only flags; the tick worker performs the dump
        # (dumping inline in signal context would re-acquire
        # non-reentrant locks the interrupted frame may hold)
        wait_until(
            lambda: glob.glob(str(tmp_path / "nh" / "dbtpu-dump-*.json")),
            timeout=5.0, what="SIGUSR2 dump file",
        )
        files = glob.glob(str(tmp_path / "nh" / "dbtpu-dump-*.json"))
        with open(files[0]) as f:
            d = json.load(f)
        assert d["traces"]["sampled"] >= 1
    finally:
        nh.stop()
    assert signal.getsignal(signal.SIGUSR2) is old
