"""Transport layer tests (reference model: ``internal/transport/*_test.go``)."""
import os
import threading
import time

import pytest

from dragonboat_tpu.server.snapshotenv import read_ss_metadata
from dragonboat_tpu.transport import (
    ChanRouter,
    ChanTransport,
    Registry,
    TCPTransport,
    Transport,
)
from dragonboat_tpu.rsm.snapshotio import SnapshotWriter
from dragonboat_tpu.wire import (
    Chunk,
    Entry,
    Membership,
    Message,
    MessageBatch,
    MessageType,
    Snapshot,
)


def wait_until(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def make_transport(addr, router, registry, received, statuses=None, tmp=None):
    def handler(batch):
        received.extend(batch.requests)

    def status_handler(cluster_id, node_id, failed):
        if statuses is not None:
            statuses.append((cluster_id, node_id, failed))

    def factory(src, rh, ch):
        return ChanTransport(src, rh, ch, router=router)

    return Transport(
        source_address=addr,
        deployment_id=1,
        registry=registry,
        raft_rpc_factory=factory,
        message_handler=handler,
        snapshot_status_handler=status_handler,
        snapshot_dir_fn=(lambda c, n: os.path.join(tmp, f"ss-{c}-{n}"))
        if tmp
        else None,
    )


def test_chan_transport_send_receive():
    router = ChanRouter()
    reg = Registry()
    reg.add(1, 1, "a:1")
    reg.add(1, 2, "b:1")
    recv_a, recv_b = [], []
    ta = make_transport("a:1", router, reg, recv_a)
    tb = make_transport("b:1", router, reg, recv_b)
    m = Message(
        type=MessageType.REPLICATE, cluster_id=1, from_=1, to=2,
        entries=[Entry(term=1, index=1, cmd=b"hello")],
    )
    assert ta.send(m)
    assert wait_until(lambda: len(recv_b) == 1)
    assert recv_b[0].entries[0].cmd == b"hello"
    ta.stop()
    tb.stop()


def test_transport_batches_queued_messages():
    router = ChanRouter()
    reg = Registry()
    reg.add(1, 2, "b:1")
    recv_b = []
    batches = []

    def handler(batch):
        batches.append(len(batch.requests))
        recv_b.extend(batch.requests)

    def factory(src, rh, ch):
        return ChanTransport(src, rh, ch, router=router)

    tb = Transport("b:1", 1, reg, factory, handler, lambda *a: None)
    ta = make_transport("a:1", router, reg, [])
    for i in range(50):
        assert ta.send(Message(
            type=MessageType.HEARTBEAT, cluster_id=1, from_=1, to=2, hint=i))
    assert wait_until(lambda: len(recv_b) == 50)
    assert max(batches) > 1  # at least some batching happened
    ta.stop()
    tb.stop()


def test_transport_unknown_target_fails_fast():
    router = ChanRouter()
    reg = Registry()
    t = make_transport("a:1", router, reg, [])
    assert not t.send(Message(type=MessageType.HEARTBEAT, cluster_id=9, to=9))
    t.stop()


def test_transport_breaker_opens_after_failures():
    router = ChanRouter()
    reg = Registry()
    reg.add(1, 2, "dead:1")  # never registered → connect fails
    unreachable = []
    recv = []

    def factory(src, rh, ch):
        return ChanTransport(src, rh, ch, router=router)

    t = Transport(
        "a:1", 1, reg, factory, lambda b: recv.extend(b.requests),
        lambda *a: None, unreachable_handler=lambda c, n: unreachable.append((c, n)),
    )
    m = Message(type=MessageType.HEARTBEAT, cluster_id=1, from_=1, to=2)
    for _ in range(5):
        t.send(m)
        time.sleep(0.05)
    assert wait_until(lambda: len(unreachable) >= 1)
    b = t.breaker("dead:1")
    assert wait_until(lambda: not b.ready() or b._failures >= 3, timeout=3)
    t.stop()


def test_chan_partition_blocks_delivery():
    router = ChanRouter()
    reg = Registry()
    reg.add(1, 2, "b:1")
    recv_b = []
    ta = make_transport("a:1", router, reg, [])
    tb = make_transport("b:1", router, reg, recv_b)
    router.partition("a:1", "b:1")
    ta.send(Message(type=MessageType.HEARTBEAT, cluster_id=1, from_=1, to=2))
    time.sleep(0.2)
    assert recv_b == []
    router.heal()
    ta.send(Message(type=MessageType.HEARTBEAT, cluster_id=1, from_=1, to=2))
    assert wait_until(lambda: len(recv_b) == 1)
    ta.stop()
    tb.stop()


def make_snapshot_file(tmp_path, payload: bytes):
    p = str(tmp_path / "snap.ss")
    w = SnapshotWriter(p)
    w.write_session(b"")
    w.write(payload)
    w.finalize()
    return p, os.path.getsize(p)


def test_snapshot_chunk_transfer_end_to_end(tmp_path):
    router = ChanRouter()
    reg = Registry()
    reg.add(1, 2, "b:1")
    recv_b = []
    statuses = []
    ta = make_transport("a:1", router, reg, [], statuses=statuses)
    tb = make_transport("b:1", router, reg, recv_b, tmp=str(tmp_path))
    payload = os.urandom(5 * 1024 * 1024)  # forces multiple 2MB chunks
    path, size = make_snapshot_file(tmp_path, payload)
    ss = Snapshot(
        filepath=path, file_size=size, index=100, term=3, cluster_id=1,
        membership=Membership(addresses={1: "a:1", 2: "b:1"}),
    )
    m = Message(
        type=MessageType.INSTALL_SNAPSHOT, cluster_id=1, from_=1, to=2,
        term=3, snapshot=ss,
    )
    assert ta.send_snapshot(m)
    assert wait_until(lambda: len(recv_b) == 1, timeout=10)
    got = recv_b[0]
    assert got.type == MessageType.INSTALL_SNAPSHOT
    assert got.snapshot.index == 100
    # image landed in the receiver's snapshot dir and is byte-identical
    assert os.path.exists(got.snapshot.filepath)
    assert os.path.getsize(got.snapshot.filepath) == size
    with open(got.snapshot.filepath, "rb") as f1, open(path, "rb") as f2:
        assert f1.read() == f2.read()
    # flag file metadata written
    meta = read_ss_metadata(os.path.dirname(got.snapshot.filepath))
    assert meta is not None and meta.index == 100
    assert wait_until(lambda: statuses == [(1, 2, False)])
    ta.stop()
    tb.stop()


def test_snapshot_out_of_order_chunk_drops_transfer(tmp_path):
    from dragonboat_tpu.transport.chunks import Chunks

    received = []
    ch = Chunks(
        deployment_id=1,
        snapshot_dir_fn=lambda c, n: str(tmp_path / f"ss-{c}-{n}"),
        message_handler=lambda b: received.extend(b.requests),
    )
    base = dict(
        cluster_id=1, node_id=2, from_=3, index=10, term=1,
        deployment_id=1, filepath="x.ss", file_size=8,
        file_chunk_count=4, chunk_count=4,
    )
    assert ch.add_chunk(Chunk(chunk_id=0, file_chunk_id=0, data=b"ab", **base))
    # skip chunk 1 → tracker must drop
    assert not ch.add_chunk(Chunk(chunk_id=2, file_chunk_id=2, data=b"cd", **base))
    # restart from 0 works
    assert ch.add_chunk(Chunk(chunk_id=0, file_chunk_id=0, data=b"ab", **base))
    ch.close()


def test_tcp_transport_roundtrip(tmp_path):
    received = []
    chunks_got = []
    ev = threading.Event()

    def rh(batch):
        received.extend(batch.requests)
        ev.set()

    def ch(c):
        chunks_got.append(c)
        return True

    server = TCPTransport("127.0.0.1:26001", rh, ch)
    server.start()
    client = TCPTransport("127.0.0.1:26002", lambda b: None, lambda c: True)
    conn = client.get_connection("127.0.0.1:26001")
    batch = MessageBatch(
        requests=[Message(
            type=MessageType.REPLICATE, cluster_id=7, from_=1, to=2,
            entries=[Entry(term=1, index=5, cmd=b"tcp-payload")],
        )],
        deployment_id=1,
        source_address="127.0.0.1:26002",
    )
    conn.send_message_batch(batch)
    assert ev.wait(timeout=5)
    assert received[0].entries[0].cmd == b"tcp-payload"
    sconn = client.get_snapshot_connection("127.0.0.1:26001")
    sconn.send_chunk(Chunk(cluster_id=7, node_id=2, chunk_id=0, data=b"zz",
                           deployment_id=1))
    assert wait_until(lambda: len(chunks_got) == 1)
    assert chunks_got[0].data == b"zz"
    conn.close()
    sconn.close()
    server.stop()


def test_tcp_rejects_corrupt_frames():
    import socket as s

    got = []
    server = TCPTransport("127.0.0.1:26003", lambda b: got.append(b), lambda c: True)
    server.start()
    sock = s.create_connection(("127.0.0.1", 26003), timeout=2)
    sock.sendall(b"\x00" * 64)  # garbage: bad magic
    time.sleep(0.2)
    # server must have dropped the connection without crashing
    sock2 = s.create_connection(("127.0.0.1", 26003), timeout=2)
    sock2.close()
    sock.close()
    assert got == []
    server.stop()
