"""vfs abstraction + ErrorFS fault-injection tests.

Reference surface: ``internal/vfs/vfs.go`` / ``memfs.go`` / ``error.go``
and the discipline of §4.7 (SURVEY.md): inject I/O errors into the
snapshot save path and prove no partial state survives.
"""
import pytest

from dragonboat_tpu import vfs
from dragonboat_tpu.rsm.snapshotio import (
    SnapshotReader,
    SnapshotWriter,
    validate_snapshot_file,
    write_witness_snapshot,
)
from dragonboat_tpu.server.snapshotenv import SSEnv, SSMode, read_ss_metadata
from dragonboat_tpu.wire import Snapshot


@pytest.fixture(params=["os", "mem"])
def fs(request, tmp_path):
    if request.param == "os":
        return vfs.OSFS(), str(tmp_path)
    return vfs.MemFS(), "/vroot"


def test_fs_file_roundtrip(fs):
    f, root = fs
    f.makedirs(root + "/d")
    with f.open(root + "/d/a.bin", "wb") as h:
        h.write(b"hello")
        f.fsync(h)
    assert f.exists(root + "/d/a.bin")
    assert f.getsize(root + "/d/a.bin") == 5
    with f.open(root + "/d/a.bin", "rb") as h:
        assert h.read() == b"hello"
    f.replace(root + "/d/a.bin", root + "/d/b.bin")
    assert not f.exists(root + "/d/a.bin")
    assert f.listdir(root + "/d") == ["b.bin"]
    f.remove(root + "/d/b.bin")
    assert not f.exists(root + "/d/b.bin")


def test_memfs_dir_rename_moves_subtree():
    f = vfs.MemFS()
    f.makedirs("/r/snapshot-01.generating")
    with f.open("/r/snapshot-01.generating/img.ss", "wb") as h:
        h.write(b"payload")
    f.replace("/r/snapshot-01.generating", "/r/snapshot-01")
    assert f.exists("/r/snapshot-01/img.ss")
    assert not f.exists("/r/snapshot-01.generating")
    with f.open("/r/snapshot-01/img.ss", "rb") as h:
        assert h.read() == b"payload"


def test_memfs_rmtree():
    f = vfs.MemFS()
    f.makedirs("/a/b/c")
    with f.open("/a/b/c/x", "wb") as h:
        h.write(b"1")
    f.rmtree("/a/b")
    assert not f.exists("/a/b/c/x")
    assert f.exists("/a")


def test_snapshot_io_on_memfs():
    f = vfs.MemFS()
    f.makedirs("/ss")
    w = SnapshotWriter("/ss/img.ss", f)
    w.write_session(b"sessions")
    w.write(b"x" * (3 * 1024 * 1024 + 17))  # multi-block payload
    w.finalize()
    assert validate_snapshot_file("/ss/img.ss", f)
    r = SnapshotReader("/ss/img.ss", f)
    assert r.read_session() == b"sessions"
    assert len(r.read(-1)) == 3 * 1024 * 1024 + 17
    r.close()
    write_witness_snapshot("/ss/w.ss", f)
    assert validate_snapshot_file("/ss/w.ss", f)


def test_ssenv_lifecycle_on_memfs():
    f = vfs.MemFS()
    f.makedirs("/root")
    env = SSEnv("/root", 7, 1, SSMode.SNAPSHOT, f)
    env.create_tmp_dir()
    with f.open(env.get_tmp_filepath(), "wb") as h:
        h.write(b"img")
    ss = Snapshot(filepath=env.get_filepath(), index=7, term=3)
    env.save_ss_metadata(ss)
    env.finalize_snapshot()
    assert f.exists(env.get_filepath())
    assert env.has_flag_file()
    meta = read_ss_metadata(env.get_final_dir(), f)
    assert meta is not None and meta.index == 7 and meta.term == 3


def test_errorfs_injects_on_write(tmp_path):
    inj = vfs.Injector.after_n(2, ops={"write"})
    f = vfs.ErrorFS(vfs.OSFS(), inj)
    h = f.open(str(tmp_path / "x"), "wb")
    h.write(b"1")
    h.write(b"2")
    with pytest.raises(OSError, match="injected"):
        h.write(b"3")
    h.close()
    assert inj.injected == 1


def test_snapshotter_save_failure_leaves_no_partial_state(tmp_path):
    """An injected failure mid-save must leave neither a final dir nor a
    temp image behind, and a retry with the fault cleared must succeed
    (reference ErrorFS discipline, vfs/error.go + snapshotter tests)."""
    from dragonboat_tpu.logdb import open_logdb
    from dragonboat_tpu.rsm.statemachine import SSMeta
    from dragonboat_tpu.snapshotter import Snapshotter
    from dragonboat_tpu.wire import Membership

    class FailEverySave:
        def __init__(self):
            self.data = b"snapshot-payload" * 1000

        def save_snapshot_payload(self, meta, w):
            w.write_session(b"")
            w.write(self.data)

    db = open_logdb("", shards=1)
    try:
        inj = vfs.Injector.after_n(1, ops={"write"}, substr=".generating")
        efs = vfs.ErrorFS(vfs.OSFS(), inj)
        root = str(tmp_path / "snapdir")
        snapper = Snapshotter(root, 1, 1, db, fs=efs)
        meta = SSMeta(
            index=10, term=2, membership=Membership(addresses={1: "a"}),
        )
        with pytest.raises(OSError, match="injected"):
            snapper.save(FailEverySave(), meta)
        # nothing but the (empty) root dir may exist
        leftover = [
            n for n in efs.fs.listdir(root)
        ]
        assert leftover == [], leftover
        # retry without faults succeeds and is committable
        ok_snapper = Snapshotter(root, 1, 1, db)
        ss, env = ok_snapper.save(FailEverySave(), meta)
        ok_snapper.commit(ss, env)
        assert validate_snapshot_file(ss.filepath)
        assert ok_snapper.get_snapshot().index == 10
    finally:
        db.close()


def test_nodehost_detects_errorfs(tmp_path):
    from dragonboat_tpu.config import ExpertConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost

    cfg = NodeHostConfig(
        node_host_dir=":memory:",
        raft_address="127.0.0.1:26000",
        expert=ExpertConfig(
            fs=vfs.ErrorFS(vfs.OSFS(), vfs.Injector(lambda op, p: False))
        ),
    )
    nh = NodeHost(cfg)
    try:
        assert nh._capture_panics
    finally:
        nh.stop()
