"""vfs abstraction + ErrorFS fault-injection tests.

Reference surface: ``internal/vfs/vfs.go`` / ``memfs.go`` / ``error.go``
and the discipline of §4.7 (SURVEY.md): inject I/O errors into the
snapshot save path and prove no partial state survives.
"""
import pytest

from dragonboat_tpu import vfs
from dragonboat_tpu.rsm.snapshotio import (
    SnapshotReader,
    SnapshotWriter,
    validate_snapshot_file,
    write_witness_snapshot,
)
from dragonboat_tpu.server.snapshotenv import SSEnv, SSMode, read_ss_metadata
from dragonboat_tpu.wire import Snapshot


@pytest.fixture(params=["os", "mem"])
def fs(request, tmp_path):
    if request.param == "os":
        return vfs.OSFS(), str(tmp_path)
    return vfs.MemFS(), "/vroot"


def test_fs_file_roundtrip(fs):
    f, root = fs
    f.makedirs(root + "/d")
    with f.open(root + "/d/a.bin", "wb") as h:
        h.write(b"hello")
        f.fsync(h)
    assert f.exists(root + "/d/a.bin")
    assert f.getsize(root + "/d/a.bin") == 5
    with f.open(root + "/d/a.bin", "rb") as h:
        assert h.read() == b"hello"
    f.replace(root + "/d/a.bin", root + "/d/b.bin")
    assert not f.exists(root + "/d/a.bin")
    assert f.listdir(root + "/d") == ["b.bin"]
    f.remove(root + "/d/b.bin")
    assert not f.exists(root + "/d/b.bin")


def test_memfs_dir_rename_moves_subtree():
    f = vfs.MemFS()
    f.makedirs("/r/snapshot-01.generating")
    with f.open("/r/snapshot-01.generating/img.ss", "wb") as h:
        h.write(b"payload")
    f.replace("/r/snapshot-01.generating", "/r/snapshot-01")
    assert f.exists("/r/snapshot-01/img.ss")
    assert not f.exists("/r/snapshot-01.generating")
    with f.open("/r/snapshot-01/img.ss", "rb") as h:
        assert h.read() == b"payload"


def test_memfs_rmtree():
    f = vfs.MemFS()
    f.makedirs("/a/b/c")
    with f.open("/a/b/c/x", "wb") as h:
        h.write(b"1")
    f.rmtree("/a/b")
    assert not f.exists("/a/b/c/x")
    assert f.exists("/a")


def test_snapshot_io_on_memfs():
    f = vfs.MemFS()
    f.makedirs("/ss")
    w = SnapshotWriter("/ss/img.ss", f)
    w.write_session(b"sessions")
    w.write(b"x" * (3 * 1024 * 1024 + 17))  # multi-block payload
    w.finalize()
    assert validate_snapshot_file("/ss/img.ss", f)
    r = SnapshotReader("/ss/img.ss", f)
    assert r.read_session() == b"sessions"
    assert len(r.read(-1)) == 3 * 1024 * 1024 + 17
    r.close()
    write_witness_snapshot("/ss/w.ss", f)
    assert validate_snapshot_file("/ss/w.ss", f)


def test_ssenv_lifecycle_on_memfs():
    f = vfs.MemFS()
    f.makedirs("/root")
    env = SSEnv("/root", 7, 1, SSMode.SNAPSHOT, f)
    env.create_tmp_dir()
    with f.open(env.get_tmp_filepath(), "wb") as h:
        h.write(b"img")
    ss = Snapshot(filepath=env.get_filepath(), index=7, term=3)
    env.save_ss_metadata(ss)
    env.finalize_snapshot()
    assert f.exists(env.get_filepath())
    assert env.has_flag_file()
    meta = read_ss_metadata(env.get_final_dir(), f)
    assert meta is not None and meta.index == 7 and meta.term == 3


def test_errorfs_injects_on_write(tmp_path):
    inj = vfs.Injector.after_n(2, ops={"write"})
    f = vfs.ErrorFS(vfs.OSFS(), inj)
    h = f.open(str(tmp_path / "x"), "wb")
    h.write(b"1")
    h.write(b"2")
    with pytest.raises(OSError, match="injected"):
        h.write(b"3")
    h.close()
    assert inj.injected == 1


def test_snapshotter_save_failure_leaves_no_partial_state(tmp_path):
    """An injected failure mid-save must leave neither a final dir nor a
    temp image behind, and a retry with the fault cleared must succeed
    (reference ErrorFS discipline, vfs/error.go + snapshotter tests)."""
    from dragonboat_tpu.logdb import open_logdb
    from dragonboat_tpu.rsm.statemachine import SSMeta
    from dragonboat_tpu.snapshotter import Snapshotter
    from dragonboat_tpu.wire import Membership

    class FailEverySave:
        def __init__(self):
            self.data = b"snapshot-payload" * 1000

        def save_snapshot_payload(self, meta, w):
            w.write_session(b"")
            w.write(self.data)

    db = open_logdb("", shards=1)
    try:
        inj = vfs.Injector.after_n(1, ops={"write"}, substr=".generating")
        efs = vfs.ErrorFS(vfs.OSFS(), inj)
        root = str(tmp_path / "snapdir")
        snapper = Snapshotter(root, 1, 1, db, fs=efs)
        meta = SSMeta(
            index=10, term=2, membership=Membership(addresses={1: "a"}),
        )
        with pytest.raises(OSError, match="injected"):
            snapper.save(FailEverySave(), meta)
        # nothing but the (empty) root dir may exist
        leftover = [
            n for n in efs.fs.listdir(root)
        ]
        assert leftover == [], leftover
        # retry without faults succeeds and is committable
        ok_snapper = Snapshotter(root, 1, 1, db)
        ss, env = ok_snapper.save(FailEverySave(), meta)
        ok_snapper.commit(ss, env)
        assert validate_snapshot_file(ss.filepath)
        assert ok_snapper.get_snapshot().index == 10
    finally:
        db.close()


def test_nodehost_detects_errorfs(tmp_path):
    from dragonboat_tpu.config import ExpertConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost

    cfg = NodeHostConfig(
        node_host_dir=":memory:",
        raft_address="127.0.0.1:26000",
        expert=ExpertConfig(
            fs=vfs.ErrorFS(vfs.OSFS(), vfs.Injector(lambda op, p: False))
        ),
    )
    nh = NodeHost(cfg)
    try:
        assert nh._capture_panics
    finally:
        nh.stop()


def test_live_cluster_survives_injected_snapshot_failure():
    """A LIVE single-replica cluster whose periodic snapshot save hits an
    injected IO fault must keep serving writes, and the NEXT periodic
    attempt (fault cleared) must land the snapshot — the reference's
    ErrorFS discipline applied at the NodeHost level, not just the
    snapshotter unit (node.go _save_snapshot failure path: log + carry
    on; no partial state)."""
    import time

    from dragonboat_tpu import Config, NodeHost, NodeHostConfig, Result
    from dragonboat_tpu.config import ExpertConfig
    from dragonboat_tpu.transport import ChanRouter, ChanTransport

    class SM:
        def __init__(self, c, n):
            self.kv = {}

        def update(self, cmd):
            k, v = cmd.decode().split("=", 1)
            self.kv[k] = v
            return Result(value=len(self.kv))

        def lookup(self, q):
            return self.kv.get(q)

        def save_snapshot(self, w, files, done):
            import json

            data = json.dumps(sorted(self.kv.items())).encode()
            w.write(len(data).to_bytes(8, "little") + data)

        def recover_from_snapshot(self, r, files, done):
            import json

            n = int.from_bytes(r.read(8), "little")
            self.kv = dict(json.loads(r.read(n).decode()))

        def close(self):
            pass

    # fail exactly the FIRST write inside a .generating temp dir, then
    # heal (after_n fails everything past the threshold — that models a
    # dead disk; this models a transient fault the retry must survive)
    seen = [0]

    def _policy(op, path):
        if op == "write" and ".generating" in path:
            seen[0] += 1
            return seen[0] == 1
        return False

    inj = vfs.Injector(_policy)
    efs = vfs.ErrorFS(vfs.OSFS(), inj)
    router = ChanRouter()
    nh = NodeHost(NodeHostConfig(
        node_host_dir=":memory:",
        rtt_millisecond=5,
        raft_address="ef1:1",
        raft_rpc_factory=lambda a, b, c: ChanTransport(a, b, c,
                                                       router=router),
        expert=ExpertConfig(fs=efs),
    ))
    try:
        nh.start_cluster(
            {1: "ef1:1"}, False, lambda c, n: SM(c, n),
            Config(cluster_id=1, node_id=1, election_rtt=10,
                   heartbeat_rtt=1, snapshot_entries=16,
                   compaction_overhead=4),
        )
        nh.get_node(1).request_campaign()
        deadline = time.time() + 15
        while time.time() < deadline:
            _, ok = nh.get_leader_id(1)
            if ok:
                break
            time.sleep(0.05)
        s = nh.get_noop_session(1)
        node = nh.get_node(1)
        # drive past snapshot_entries: the first periodic save fails on
        # the injected write; the cluster must keep committing
        for j in range(40):
            rs = nh.propose(s, f"k{j}=v{j}".encode(), timeout=15.0)
            assert rs.wait(30.0).completed
        # the save runs on the snapshot pool; poll rather than assert
        # (nothing synchronizes the proposes with the pool thread)
        deadline = time.time() + 30
        while time.time() < deadline and inj.injected < 1:
            time.sleep(0.05)
        assert inj.injected >= 1, "fault never reached the save path"
        # keep writing; the healed retries must land a snapshot
        deadline = time.time() + 60
        j = 40
        while time.time() < deadline and node.sm.get_snapshot_index() == 0:
            rs = nh.propose(s, f"k{j}=v{j}".encode(), timeout=15.0)
            assert rs.wait(30.0).completed
            j += 1
            time.sleep(0.02)
        assert node.sm.get_snapshot_index() > 0, (
            "snapshot never recovered after the injected failure"
        )
        assert nh.sync_read(1, "k0", timeout=15.0) == "v0"
    finally:
        nh.stop()
