"""AOT warm-compile + persistent XLA compilation cache gate (ISSUE 7).

The fast cpu gate behind ``make test-warmup``: the warmup pass runs
against a TEMP compilation-cache directory and the suite asserts the two
contracts the tentpole rests on:

(a) a second enable is CACHE-HOT — after ``jax.clear_caches()`` (the
    in-process twin of a restart) re-warming a fresh engine deserializes
    every program from the persistent cache (hits > 0, misses == 0)
    instead of recompiling;
(b) proposals issued DURING warmup never block on compilation — the
    round thread stays on the already-compiled single-round path until
    the readiness latch flips (``fused_dispatches == 0`` while warming,
    ``fuse_skip="warmup"`` on the round spans), and commits keep landing
    the whole time.
"""
import threading
import time

import pytest

jax = pytest.importorskip("jax")

from dragonboat_tpu.ops.engine import (  # noqa: E402
    WARM_K_BUCKETS,
    BatchedQuorumEngine,
    compilation_cache_stats,
    enable_persistent_compilation_cache,
    k_bucket,
    kernel_source_hash,
)


def test_k_bucket_covers_the_adaptive_range():
    assert WARM_K_BUCKETS == tuple(sorted(WARM_K_BUCKETS))
    assert k_bucket(1) == WARM_K_BUCKETS[0]
    for k in range(1, max(WARM_K_BUCKETS) + 1):
        b = k_bucket(k)
        assert b >= k and b in WARM_K_BUCKETS
    # beyond the largest bucket clamps (callers cap K there)
    assert k_bucket(10 * max(WARM_K_BUCKETS)) == max(WARM_K_BUCKETS)


def test_kernel_source_hash_is_stable():
    assert kernel_source_hash() == kernel_source_hash()
    assert len(kernel_source_hash()) == 64


def test_second_enable_is_cache_hot(tmp_path):
    """(a): cold warmup populates the persistent cache; after clearing
    the in-memory jit caches, a fresh engine's warmup is served entirely
    from disk."""
    versioned = enable_persistent_compilation_cache(str(tmp_path / "cc"))
    assert kernel_source_hash()[:16] in versioned

    # earlier tests in the same process may already hold these programs
    # in the in-memory jit cache (no compile → no cache-miss events);
    # drop them so the cold warmup genuinely compiles into the temp dir
    jax.clear_caches()
    eng = BatchedQuorumEngine(16, 4, event_cap=64)
    s0 = compilation_cache_stats()
    stats = eng.warmup_fused(k_buckets=(4,), background=False)
    assert stats["error"] is None
    assert eng.fused_ready
    # 2 fused (reads on/off) + 2 sparse + 2 sparse-votes (tick on/off)
    # + 2 dense read
    assert stats["programs"] == 8
    s1 = compilation_cache_stats()
    assert s1["misses"] > s0["misses"], "cold warmup must populate the cache"

    # the in-process twin of a restart: drop every in-memory executable
    jax.clear_caches()
    eng2 = BatchedQuorumEngine(16, 4, event_cap=64)
    st2 = eng2.warmup_fused(k_buckets=(4,), background=False)
    assert st2["error"] is None
    assert eng2.fused_ready
    assert st2["cache_hits"] > 0, "second enable must hit the persistent cache"
    assert st2["cache_misses"] == 0, (
        f"second enable recompiled {st2['cache_misses']} programs"
    )


def test_warmup_failure_leaves_single_round_path(monkeypatch):
    """A warmup that dies must leave the latch unset (the coordinator
    simply stays on the single-round path) — never a crashed engine."""
    eng = BatchedQuorumEngine(8, 3, event_cap=32)
    monkeypatch.setattr(
        eng, "_warm_one",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")),
    )
    stats = eng.warmup_fused(k_buckets=(4,), background=False)
    assert stats["error"] is not None
    assert not eng.fused_ready


class FakeNode:
    """Minimal node shim (the test_device_ticks pattern): commit effects
    re-checked under raftMu with the scalar guards intact."""

    def __init__(self, cid, raft):
        self.cluster_id = cid
        self.raft_mu = threading.RLock()

        class _P:
            pass

        self.peer = _P()
        self.peer.raft = raft
        self.commits = []

    def offload_commit(self, q):
        r = self.peer.raft
        with self.raft_mu:
            if r.is_leader() and r.log.try_commit(q, r.term):
                self.commits.append(q)

    def offload_election(self, won, term):
        pass

    def offload_tick_elect(self):
        pass

    def offload_tick_heartbeat(self):
        pass

    def offload_tick_demote(self):
        pass


def _mk_coord_cluster(n_groups=4, warm=False):
    from dragonboat_tpu.raft import InMemLogDB
    from dragonboat_tpu.tpuquorum import TpuQuorumCoordinator
    from tests.raft_harness import new_test_raft

    coord = TpuQuorumCoordinator(
        capacity=64, n_peers=4, drive_ticks=True, interval_s=60.0,
        warm_fused=warm,
    )
    nodes = {}
    for g in range(n_groups):
        cid = 1 + g
        r = new_test_raft(1, [1, 2, 3], 10, 1, InMemLogDB())
        r.cluster_id = cid
        r.become_candidate()
        r.become_leader()
        n = FakeNode(cid, r)
        r.offload = coord
        nodes[cid] = n
        coord._nodes[cid] = n
        with coord._mu:
            coord._sync_row_locked(n)
    coord.flush()
    return coord, nodes


def _drive_round(coord, nodes, ticks=4):
    """One write per group + a tick burst, flushed synchronously."""
    from dragonboat_tpu.wire import Entry

    for cid, n in nodes.items():
        r = n.peer.raft
        with n.raft_mu:
            r.append_entries([Entry(cmd=b"w")])
            idx = r.log.last_index()
        coord.ack(cid, 2, idx)
        coord.ack(cid, 3, idx)
    for _ in range(ticks):
        coord.request_tick()
    coord.flush()


def test_proposals_never_block_on_warmup():
    """(b): while the warmup thread compiles, rounds keep completing on
    the single-round path — zero fused dispatches before the latch, the
    skip reason on record, commits landing throughout; after the latch, a
    tick backlog fuses and no dispatch span ever stalls."""
    coord, nodes = _mk_coord_cluster(warm=False)
    try:
        obs = coord.enable_obs()
        obs.recorder.stall_ms = 1000.0
        t = coord.start_warmup()
        assert t is not None
        rounds_during_warm = 0
        while not coord.eng.fused_ready and rounds_during_warm < 2000:
            _drive_round(coord, nodes, ticks=4)
            rounds_during_warm += 1
            if not coord.eng.fused_ready:
                # every round that ran before the latch stayed on the
                # already-compiled single-round programs
                assert coord.fused_dispatches == 0
        t.join(timeout=300)
        assert coord.eng.fused_ready, coord.warmup_stats
        assert coord.warmup_stats["error"] is None
        # commits landed the whole time (proposals were never stalled
        # behind the compile thread)
        for cid, n in nodes.items():
            r = n.peer.raft
            assert r.log.committed == r.log.last_index(), (
                cid, r.log.committed, r.log.last_index(),
            )
        spans = obs.recorder.spans()
        if rounds_during_warm:
            assert any(
                s.get("fuse_skip") == "warmup" for s in spans
                if s["kind"] == "coord_round"
            ), "deficit rounds during warmup must record the skip reason"
        assert any(s["kind"] == "warmup" for s in spans)

        # after the latch: a tick backlog replays as ONE fused dispatch
        before = coord.fused_dispatches
        _drive_round(coord, nodes, ticks=6)
        assert coord.fused_dispatches == before + 1
        fused_spans = [
            s for s in obs.recorder.spans() if s["kind"] == "fused"
        ]
        assert any(s.get("k_rounds", 0) > 1 for s in fused_spans)
        # the tentpole's headline contract: nothing on the dispatch path
        # ever hit the stall watchdog (a first-use compile would)
        assert not any(
            s.get("stalled") for s in obs.recorder.spans()
            if s["kind"] in ("fused", "dispatch")
        )
        for cid, n in nodes.items():
            r = n.peer.raft
            assert r.log.committed == r.log.last_index()
    finally:
        coord.stop()


def test_warmup_metrics_published():
    """The ``dragonboat_device_warmup_seconds`` family lands in the
    registry the moment obs is enabled, and accumulates once warmup
    runs."""
    from dragonboat_tpu.events import MetricsRegistry
    from dragonboat_tpu.obs import FlightRecorder

    reg = MetricsRegistry()
    eng = BatchedQuorumEngine(8, 3, event_cap=32)
    eng.enable_obs(recorder=FlightRecorder(), registry=reg)
    import io

    buf = io.StringIO()
    reg.write_health_metrics(buf)
    assert "dragonboat_device_warmup_seconds" in buf.getvalue()
    stats = eng.warmup_fused(
        k_buckets=(4,), include_single=False, background=False
    )
    assert stats["error"] is None
    buf = io.StringIO()
    reg.write_health_metrics(buf)
    text = buf.getvalue()
    assert "dragonboat_device_warmup_programs_total 2" in text
    # warmup spans carry the variant + compile wall, and never trip the
    # stall watchdog (compile_ms is not a watchdog field)
    spans = [s for s in eng._obs.recorder.spans() if s["kind"] == "warmup"]
    assert len(spans) == 2
    assert all("compile_ms" in s and not s.get("stalled") for s in spans)
