"""Wire type + codec roundtrip tests (reference raftpb fuzz/marshal tests)."""
import pytest

from dragonboat_tpu.wire import (
    Bootstrap,
    Chunk,
    ConfigChange,
    ConfigChangeType,
    Entry,
    EntryType,
    Membership,
    Message,
    MessageBatch,
    MessageType,
    Snapshot,
    SnapshotFile,
    State,
    StateMachineType,
    codec,
)


def test_entry_roundtrip():
    e = Entry(
        term=3,
        index=1000000,
        type=EntryType.CONFIG_CHANGE,
        key=2**63,
        client_id=42,
        series_id=7,
        responded_to=6,
        cmd=b"hello world",
    )
    assert codec.decode_entry(codec.encode_entry(e)) == e


def test_entry_defaults_roundtrip():
    e = Entry()
    assert codec.decode_entry(codec.encode_entry(e)) == e


def test_entry_batch_roundtrip():
    batch = [Entry(term=i, index=i, cmd=bytes([i])) for i in range(48)]
    assert codec.decode_entry_batch(codec.encode_entry_batch(batch)) == batch


def test_state_roundtrip():
    st = State(term=5, vote=2, commit=99)
    assert codec.decode_state(codec.encode_state(st)) == st


def test_membership_roundtrip_deterministic():
    m = Membership(
        config_change_id=9,
        addresses={3: "c:3", 1: "a:1", 2: "b:2"},
        removed={5: True},
        observers={7: "o:7"},
        witnesses={9: "w:9"},
    )
    data1 = codec.encode_membership(m)
    # insertion order must not affect bytes (determinism for state hashing)
    m2 = Membership(
        config_change_id=9,
        addresses={1: "a:1", 2: "b:2", 3: "c:3"},
        removed={5: True},
        observers={7: "o:7"},
        witnesses={9: "w:9"},
    )
    assert data1 == codec.encode_membership(m2)
    assert codec.decode_membership(data1) == m


def test_snapshot_roundtrip():
    ss = Snapshot(
        filepath="/tmp/snap.gbsnap",
        file_size=12345,
        index=100,
        term=3,
        membership=Membership(addresses={1: "a:1"}),
        files=[SnapshotFile(filepath="/x", file_size=5, file_id=1, metadata=b"m")],
        checksum=b"\x01\x02",
        dummy=True,
        cluster_id=7,
        type=StateMachineType.REGULAR,
        imported=True,
        on_disk_index=55,
        witness=False,
    )
    assert codec.decode_snapshot(codec.encode_snapshot(ss)) == ss


def test_message_roundtrip():
    m = Message(
        type=MessageType.REPLICATE,
        to=2,
        from_=1,
        cluster_id=77,
        term=3,
        log_term=2,
        log_index=10,
        commit=9,
        reject=True,
        hint=123,
        hint_high=456,
        entries=[Entry(term=3, index=11, cmd=b"x")],
        snapshot=Snapshot(index=5, term=1),
    )
    got = codec.decode_message(codec.encode_message(m))
    assert got == m


def test_message_batch_roundtrip():
    b = MessageBatch(
        requests=[
            Message(type=MessageType.HEARTBEAT, to=1, from_=2, cluster_id=3),
            Message(type=MessageType.REPLICATE_RESP, to=2, from_=1, reject=True),
        ],
        deployment_id=88,
        source_address="host:1234",
        bin_ver=1,
    )
    assert codec.decode_message_batch(codec.encode_message_batch(b)) == b


def test_config_change_roundtrip():
    cc = ConfigChange(
        config_change_id=4,
        type=ConfigChangeType.ADD_WITNESS,
        node_id=5,
        address="h:1",
        initialize=True,
    )
    assert codec.decode_config_change(codec.encode_config_change(cc)) == cc


def test_bootstrap_roundtrip():
    b = Bootstrap(addresses={1: "a:1", 2: "b:2"}, join=False,
                  type=StateMachineType.ON_DISK)
    assert codec.decode_bootstrap(codec.encode_bootstrap(b)) == b
    assert b.validate()
    assert not Bootstrap().validate()
    assert Bootstrap(join=True).validate()


def test_chunk_roundtrip():
    c = Chunk(
        cluster_id=1,
        node_id=2,
        from_=3,
        chunk_id=4,
        chunk_size=5,
        chunk_count=6,
        data=b"payload",
        index=7,
        term=8,
        membership=Membership(addresses={1: "a:1"}),
        filepath="f",
        file_size=9,
        deployment_id=10,
        file_chunk_id=11,
        file_chunk_count=12,
        has_file_info=True,
        file_info=SnapshotFile(filepath="g", file_size=1, file_id=2),
        bin_ver=13,
        on_disk_index=14,
        witness=True,
    )
    assert codec.decode_chunk(codec.encode_chunk(c)) == c


def test_corrupt_data_raises():
    e = Entry(term=1, index=2, cmd=b"abc")
    data = codec.encode_entry(e)
    with pytest.raises(codec.CodecError):
        codec.decode_entry(data + b"\x00")
    with pytest.raises(codec.CodecError):
        codec.decode_entry(data[:-1])


def test_entry_session_predicates():
    from dragonboat_tpu.wire import (
        NOOP_CLIENT_ID,
        SERIES_ID_FOR_REGISTER,
        SERIES_ID_FOR_UNREGISTER,
    )

    e = Entry(client_id=NOOP_CLIENT_ID)
    assert e.is_noop_session()
    assert not e.is_session_managed()
    reg = Entry(client_id=5, series_id=SERIES_ID_FOR_REGISTER)
    assert reg.is_new_session_request()
    unreg = Entry(client_id=5, series_id=SERIES_ID_FOR_UNREGISTER)
    assert unreg.is_end_of_session_request()
