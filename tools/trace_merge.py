#!/usr/bin/env python
"""Join per-host ``NodeHost.dump_trace`` Perfetto dumps into ONE
timeline (ISSUE 14).

Each host's dump renders only its own half of a sampled replication —
the leader's request flow (propose → raft_step → repl_quorum → wal →
apply → egress) on one host, the ``follower_append`` /
``follower_fsync`` / ``ack_send`` leg slices on the others.  This tool
merges N dumps so one proposal reads as a SINGLE flow spanning leader
and followers:

- every host becomes its own Perfetto process (``pid``), named by its
  raft address (``metadata.host``);
- follower timestamps shift onto the leader's clock using the leader's
  NTP-style ack-pair offset estimates (``metadata.repl_offsets``:
  peer address → follower-minus-leader seconds, estimated by
  obs/replattr.py from the four send/recv/ack stamps each sampled
  replication carries).  The estimate's residual error is the wire
  asymmetry — the classic NTP caveat (docs/overview.md);
- flow ids are remapped per ORIGINATING host (the leader whose trace id
  the flow carries — follower leg events name their origin), so two
  hosts' independently-numbered trace ids can never collide in the
  merged file.

Usage::

    python tools/trace_merge.py -o merged.json leader.json f1.json f2.json

Load ``merged.json`` at https://ui.perfetto.dev — the leader's
``write-<tid>`` flow now steps through the follower processes'
replication slices.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple


def _host_of(dump: dict, fallback: str) -> str:
    md = dump.get("metadata") or {}
    return md.get("host") or fallback


def _offset_for(addr: str, dumps: List[dict],
                _seen: Optional[frozenset] = None) -> Optional[float]:
    """The clock offset (seconds, ``addr``'s clock minus the reference
    clock) for one host, from the first dump whose leader-side
    attribution estimated it.  The REFERENCE dump is dumps[0]; offsets
    estimated by a non-reference leader chain through that leader's own
    offset so everything lands on one clock."""
    ref_host = _host_of(dumps[0], "")
    if addr == ref_host:
        return 0.0
    seen = _seen or frozenset()
    if addr in seen:
        return None  # estimate cycle (two leaders estimating each other)
    # direct estimate from the reference host's leader-side attribution
    ref_offs = (dumps[0].get("metadata") or {}).get("repl_offsets") or {}
    if addr in ref_offs:
        return float(ref_offs[addr])
    # chained: some other dump estimated addr, and the reference (or a
    # prior chain step) estimated THAT dump's host
    for d in dumps[1:]:
        offs = (d.get("metadata") or {}).get("repl_offsets") or {}
        if addr in offs:
            base = _offset_for(_host_of(d, ""), dumps, seen | {addr})
            if base is not None:
                return base + float(offs[addr])
    return None


def merge_dumps(dumps: List[dict]) -> dict:
    """Merge dump dicts (``NodeHost.dump_trace`` return values), first
    one is the reference clock (normally the leader — its dump carries
    the ``repl_offsets`` the shift needs)."""
    if not dumps:
        raise ValueError("no dumps to merge")
    hosts = [_host_of(d, f"host{i}") for i, d in enumerate(dumps)]
    events: List[dict] = []
    flow_ids: Dict[Tuple[str, int], int] = {}
    unsynced: List[str] = []

    def flow_id(origin: str, tid: int) -> int:
        key = (origin, tid)
        fid = flow_ids.get(key)
        if fid is None:
            fid = flow_ids[key] = len(flow_ids) + 1
        return fid

    shifts = {}
    for i, (host, dump) in enumerate(zip(hosts, dumps)):
        off = _offset_for(host, dumps)
        if off is None:
            unsynced.append(host)
            off = 0.0
        shifts[host] = off
        pid = i + 1
        events.append({
            "ph": "M", "name": "process_name", "pid": pid,
            "args": {"name": host},
        })
        shift_us = off * 1e6
        for ev in dump.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") != "M" and "ts" in ev:
                # follower stamps ride onto the reference clock
                ev["ts"] = round(ev["ts"] - shift_us, 1)
            if "id" in ev:
                args = ev.get("args") or {}
                origin = args.get("origin") or host
                ev["id"] = flow_id(origin, ev["id"])
            events.append(ev)
    return {
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "metadata": {
            "merged_hosts": hosts,
            "reference_host": hosts[0],
            "clock_shift_ms": {
                h: round(s * 1e3, 4) for h, s in shifts.items()
            },
            # hosts with no ack-pair estimate stay on their own clock —
            # their slices still render, just unshifted
            "unsynced_hosts": unsynced,
            "flows": len(flow_ids),
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dumps", nargs="+", help="per-host dump_trace JSON "
                    "files; FIRST one is the reference clock (leader)")
    ap.add_argument("-o", "--out", default="merged_trace.json")
    args = ap.parse_args(argv)
    loaded = []
    for p in args.dumps:
        with open(p) as f:
            loaded.append(json.load(f))
    merged = merge_dumps(loaded)
    with open(args.out, "w") as f:
        json.dump(merged, f)
    md = merged["metadata"]
    print(
        f"merged {len(loaded)} hosts -> {args.out}: "
        f"{len(merged['traceEvents'])} events, {md['flows']} flows, "
        f"shifts {md['clock_shift_ms']} ms"
        + (f", UNSYNCED {md['unsynced_hosts']}" if md["unsynced_hosts"]
           else "")
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
